// edl-coordinator — TCP service wrapping edl::Coordinator for multi-host
// jobs (the standalone analog of the reference's master+etcd pod,
// reference: pkg/jobparser.go:186-227). Line protocol, thread per
// connection, values must be newline-free (discovery strings).
//
//   PUT <key> <value...>        -> OK
//   GET <key>                   -> VAL <value...> | NONE
//   DEL <key>                   -> OK
//   REG <worker> <incarnation>  -> EPOCH <n>
//   HB <worker>                 -> OK | UNKNOWN
//   LEAVE <worker>              -> EPOCH <n>
//   EXPIRE                      -> EPOCH <n>
//   EPOCH                       -> EPOCH <n>
//   MEMBERS                     -> MEMBERS name:inc:rank,... | MEMBERS
//   BARRIER <name> <worker>     -> COUNT <n>
//   BCOUNT <name>               -> COUNT <n>
//   QINIT <n> <chunk> <passes> <timeout_s> -> OK
//   LEASE <worker>              -> TASK <id> <start> <end> <epoch> | NONE
//   ACK <id> / NACK <id>        -> OK | UNKNOWN
//   RELEASE <worker>            -> COUNT <n>
//   QDONE                       -> DONE 0|1
//   QSTATS                      -> STATS todo leased done dead epoch
//   PING                        -> PONG
//   TIME                        -> TIME <epoch_micros>   (clock sync)
//
// Chip-lease ops (the distributed ChipLeaseBroker backend; holders and
// tokens must be space-free, ":" is fine). Old servers answer
// "ERR unknown command" and clients degrade gracefully (TIME pattern):
//   LINIT <total>               -> OK <total> | ERR busy
//   LGRANT <holder> <chips> <token> -> LEASE <id> <epoch> <chips>
//                                    | ERR nochips <free> | ERR nopool
//   LRECALL <id>                -> OK | ERR unknown | ERR freed
//   LFREE <id>                  -> OK <chips> | ERR unknown | ERR freed
//   LCONFIRM <id> <epoch>       -> OK <epoch>
//                                | FENCED stale_epoch|freed|unknown
//   LCRASH <holder>             -> OK <chips>
//   LEXPIRE                     -> OK <released> <recovering>
//   LSNAP                       -> LEASES <pool> <free> <epoch> <recov>
//                                  [id|holder|chips|epoch|state|conf,...]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <sstream>
#include <string>
#include <thread>

#include "coordinator.h"

namespace {

edl::Coordinator* g_coord = nullptr;

std::string Handle(const std::string& line) {
  std::istringstream in(line);
  std::string cmd;
  in >> cmd;
  auto rest_of_line = [&in]() {
    std::string rest;
    std::getline(in, rest);
    if (!rest.empty() && rest[0] == ' ') rest.erase(0, 1);
    return rest;
  };
  if (cmd == "PING") return "PONG";
  if (cmd == "TIME") {
    // the fleet's reference wall clock: workers bracket this round
    // trip to estimate their offset (NTP midpoint, obs/disttrace.py)
    auto now = std::chrono::system_clock::now().time_since_epoch();
    auto us = std::chrono::duration_cast<std::chrono::microseconds>(now);
    return "TIME " + std::to_string(us.count());
  }
  if (cmd == "PUT") {
    std::string k;
    in >> k;
    g_coord->KvPut(k, rest_of_line());
    return "OK";
  }
  if (cmd == "GET") {
    std::string k, v;
    in >> k;
    return g_coord->KvGet(k, &v) ? "VAL " + v : "NONE";
  }
  if (cmd == "DEL") {
    std::string k;
    in >> k;
    g_coord->KvDel(k);
    return "OK";
  }
  if (cmd == "REG") {
    std::string w;
    long long inc = 0;
    in >> w >> inc;
    return "EPOCH " + std::to_string(g_coord->Register(w, inc));
  }
  if (cmd == "HB") {
    std::string w;
    in >> w;
    return g_coord->Heartbeat(w) ? "OK" : "UNKNOWN";
  }
  if (cmd == "LEAVE") {
    std::string w;
    in >> w;
    return "EPOCH " + std::to_string(g_coord->Leave(w));
  }
  if (cmd == "EXPIRE") return "EPOCH " + std::to_string(g_coord->ExpireMembers());
  if (cmd == "EPOCH") return "EPOCH " + std::to_string(g_coord->Epoch());
  if (cmd == "MEMBERS") {
    std::string s;
    for (const auto& m : g_coord->Members()) {
      if (!s.empty()) s += ',';
      s += m.name + ":" + std::to_string(m.incarnation) + ":" +
           std::to_string(m.rank);
    }
    return "MEMBERS " + s;
  }
  if (cmd == "BARRIER") {
    std::string name, w;
    in >> name >> w;
    return "COUNT " + std::to_string(g_coord->BarrierArrive(name, w));
  }
  if (cmd == "BCOUNT") {
    std::string name;
    in >> name;
    return "COUNT " + std::to_string(g_coord->BarrierCount(name));
  }
  if (cmd == "QINIT") {
    long long n = 0, chunk = 0;
    int passes = 1;
    double timeout = 16.0;
    in >> n >> chunk >> passes >> timeout;
    g_coord->QueueInit(n, chunk, passes, timeout);
    return "OK";
  }
  if (cmd == "LEASE") {
    std::string w;
    in >> w;
    edl::Task t;
    if (!g_coord->Lease(w, &t)) return "NONE";
    return "TASK " + std::to_string(t.id) + " " + std::to_string(t.start) +
           " " + std::to_string(t.end) + " " + std::to_string(t.epoch);
  }
  if (cmd == "ACK" || cmd == "NACK") {
    long long id = -1;
    in >> id;
    bool ok = cmd == "ACK" ? g_coord->Ack(id) : g_coord->Nack(id);
    return ok ? "OK" : "UNKNOWN";
  }
  if (cmd == "RELEASE") {
    std::string w;
    in >> w;
    return "COUNT " + std::to_string(g_coord->ReleaseWorker(w));
  }
  if (cmd == "QDONE") return std::string("DONE ") + (g_coord->QueueDone() ? "1" : "0");
  if (cmd == "QSTATS") {
    int64_t s[5];
    g_coord->QueueStats(s);
    std::string out = "STATS";
    for (int i = 0; i < 5; ++i) out += " " + std::to_string(s[i]);
    return out;
  }
  if (cmd == "LINIT") {
    long long total = 0;
    in >> total;
    if (!g_coord->LeaseInit(total)) return "ERR busy";
    return "OK " + std::to_string(total);
  }
  if (cmd == "LGRANT") {
    std::string holder, token;
    long long chips = 0;
    in >> holder >> chips >> token;
    int64_t out[2];
    int64_t id = g_coord->LeaseGrant(holder, chips, token, out);
    if (id == -2) return "ERR nopool";
    if (id == -1) return "ERR nochips " + std::to_string(out[1]);
    return "LEASE " + std::to_string(id) + " " + std::to_string(out[0]) +
           " " + std::to_string(out[1]);
  }
  if (cmd == "LRECALL") {
    long long id = -1;
    in >> id;
    int rc = g_coord->LeaseRecall(id);
    if (rc == -1) return "ERR unknown";
    if (rc == -2) return "ERR freed";
    return "OK";
  }
  if (cmd == "LFREE") {
    long long id = -1;
    in >> id;
    long long chips = g_coord->LeaseFree(id);
    if (chips == -1) return "ERR unknown";
    if (chips == -2) return "ERR freed";
    return "OK " + std::to_string(chips);
  }
  if (cmd == "LCONFIRM") {
    long long id = -1, epoch = -1;
    in >> id >> epoch;
    int rc = g_coord->LeaseConfirm(id, epoch);
    if (rc == 1) return "FENCED stale_epoch";
    if (rc == 2) return "FENCED freed";
    if (rc == 3) return "FENCED unknown";
    return "OK " + std::to_string(epoch);
  }
  if (cmd == "LCRASH") {
    std::string holder;
    in >> holder;
    return "OK " + std::to_string(g_coord->LeaseCrashed(holder));
  }
  if (cmd == "LEXPIRE") {
    int64_t o[2];
    g_coord->LeaseExpire(o);
    return "OK " + std::to_string(o[0]) + " " + std::to_string(o[1]);
  }
  if (cmd == "LSNAP") return "LEASES " + g_coord->LeaseSnap();
  if (cmd == "COMPACT") {  // snapshot+truncate the WAL now
    g_coord->Compact();
    return "OK";
  }
  if (cmd == "WALSTATS") {
    int64_t s[2];
    g_coord->WalStats(s);
    return "WAL " + std::to_string(s[0]) + " " + std::to_string(s[1]);
  }
  return "ERR unknown command";
}

void Serve(int fd) {
  std::string buf;
  char chunk[4096];
  for (;;) {
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) break;
    buf.append(chunk, static_cast<size_t>(n));
    size_t pos;
    while ((pos = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, pos);
      buf.erase(0, pos + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::string resp = Handle(line) + "\n";
      if (write(fd, resp.data(), resp.size()) < 0) {
        close(fd);
        return;
      }
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 7164;  // the reference's default job port (pkg/jobparser.go:50)
  double ttl = 10.0;
  const char* wal = "";
  long long compact_bytes = 0;  // 0 = library default (1 MiB)
  double lease_recover = -1.0;  // <0 = library default (5 s)
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--member-ttl")) ttl = atof(argv[i + 1]);
    // chip-lease recovery window: seconds a restarted broker waits for
    // holders to re-confirm before force-releasing the silent ones
    if (!strcmp(argv[i], "--lease-recover")) lease_recover = atof(argv[i + 1]);
    // durability: replay + append the write-ahead log (etcd analog) —
    // a restarted coordinator resumes with exact KV/queue accounting
    if (!strcmp(argv[i], "--wal")) wal = argv[i + 1];
    // WAL auto-compaction threshold: snapshot+truncate once this many
    // bytes have been appended since the last compaction
    if (!strcmp(argv[i], "--wal-compact-bytes"))
      compact_bytes = atoll(argv[i + 1]);
  }
  signal(SIGPIPE, SIG_IGN);
  if (wal[0]) {
    // preflight: refuse to start "durable" without a writable WAL
    FILE* f = fopen(wal, "a");
    if (!f) {
      printf("edl-coordinator: cannot open WAL %s\n", wal);
      fflush(stdout);
      return 1;
    }
    fclose(f);
  }
  g_coord = new edl::Coordinator(ttl, wal);
  if (compact_bytes > 0) g_coord->SetWalCompactBytes(compact_bytes);
  if (lease_recover >= 0) g_coord->SetLeaseRecoverWindow(lease_recover);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 64) != 0) {
    perror("listen");
    return 1;
  }
  // readiness line on stdout (the launcher greps for it)
  printf("edl-coordinator listening on %d\n", port);
  fflush(stdout);
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(Serve, fd).detach();
  }
}
