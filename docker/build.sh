#!/bin/bash
# Build the controller and worker images. Port of the reference's
# image build script (reference: docker/build.sh:1-44, which produced
# CPU and GPU runtime variants); here the variants are a CPU-only
# controller image and a TPU worker image.
set -euo pipefail
cd "$(dirname "$0")/.."

TAG=${TAG:-latest}
REGISTRY=${REGISTRY:-edl-tpu}
# TPU worker base: any image with Python >= 3.10; jax[tpu] is pulled in
# at build time. Override for an air-gapped registry mirror.
WORKER_BASE=${WORKER_BASE:-python:3.11-slim}

docker build -f docker/Dockerfile.controller -t "${REGISTRY}/controller:${TAG}" .
docker build -f docker/Dockerfile.worker --build-arg "BASE=${WORKER_BASE}" \
    -t "${REGISTRY}/worker:${TAG}" .

echo "built ${REGISTRY}/controller:${TAG} and ${REGISTRY}/worker:${TAG}"
