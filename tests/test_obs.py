"""Unified telemetry (edl_tpu/obs): registry semantics, Prometheus
text exposition (golden), live exporter scrape, fleet push/aggregate,
tracer bridge, and the monitor-source round trips."""

import json
import threading
import urllib.request

import pytest

from edl_tpu import obs
from edl_tpu.monitor.collector import (
    MonitorSample,
    ServingSource,
    StoreSource,
)
from edl_tpu.obs.metrics import percentile_from_buckets
from edl_tpu.utils import tracing


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_gauge_histogram_basics():
    r = obs.MetricsRegistry()
    c = r.counter("edl_t_total", "t", ("kind",))
    c.inc(kind="a")
    c.inc(2, kind="a")
    c.inc(kind="b")
    assert c.value(kind="a") == 3 and c.value(kind="b") == 1
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")  # counters only go up
    g = r.gauge("edl_t_gauge", "g")
    g.set(7)
    g.set(3.5)
    assert g.value() == 3.5
    h = r.histogram("edl_t_seconds", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    st = h.stats()
    assert st["count"] == 4 and st["sum"] == pytest.approx(6.05)
    # p50 lands inside the (0.1, 1.0] bucket
    assert 0.1 < h.percentile(0.5) <= 1.0
    # +Inf clamps to the largest finite edge
    h.observe(100.0)
    assert h.percentile(0.999) == 10.0


def test_get_or_create_and_schema_collision():
    r = obs.MetricsRegistry()
    a = r.counter("edl_same_total", "x", ("k",))
    b = r.counter("edl_same_total", "x", ("k",))
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("edl_same_total", "x", ("k",))  # kind clash
    with pytest.raises(ValueError):
        r.counter("edl_same_total", "x", ("other",))  # label clash
    with pytest.raises(ValueError):
        a.inc(k="v", extra="nope")  # unknown label


def test_weighted_histogram_observations():
    r = obs.MetricsRegistry()
    h = r.histogram("edl_w_seconds", "w", buckets=(0.01, 0.1, 1.0))
    h.observe(0.05, n=7)  # one drain, 7 tokens at the per-token mean
    st = h.stats()
    assert st["count"] == 7 and st["sum"] == pytest.approx(0.35)


def test_registry_thread_safety_under_contention():
    r = obs.MetricsRegistry()
    c = r.counter("edl_race_total", "r")
    h = r.histogram("edl_race_seconds", "r")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.01)

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value() == 4000
    assert h.stats()["count"] == 4000


# ---------------------------------------------------------------------------
# exposition: golden text + parse round trip


def test_prometheus_text_golden():
    """Pin the exposition format: HELP/TYPE lines, label quoting,
    cumulative buckets, sum/count, value formatting."""
    r = obs.MetricsRegistry()
    r.counter("edl_req_total", "requests by event", ("event",)).inc(
        3, event="ok"
    )
    r.gauge("edl_depth", "queue depth").set(2)
    h = r.histogram("edl_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert r.render() == (
        "# HELP edl_depth queue depth\n"
        "# TYPE edl_depth gauge\n"
        "edl_depth 2\n"
        "# HELP edl_lat_seconds latency\n"
        "# TYPE edl_lat_seconds histogram\n"
        'edl_lat_seconds_bucket{le="0.1"} 1\n'
        'edl_lat_seconds_bucket{le="1.0"} 2\n'
        'edl_lat_seconds_bucket{le="+Inf"} 3\n'
        "edl_lat_seconds_sum 5.55\n"
        "edl_lat_seconds_count 3\n"
        "# HELP edl_req_total requests by event\n"
        "# TYPE edl_req_total counter\n"
        'edl_req_total{event="ok"} 3\n'
    )


def test_label_escaping():
    r = obs.MetricsRegistry()
    r.counter("edl_esc_total", "e", ("path",)).inc(path='a"b\\c\nd')
    text = r.render()
    assert 'path="a\\"b\\\\c\\nd"' in text
    parsed = obs.parse_prometheus_text(text)
    (labels, v), = parsed["edl_esc_total"]
    assert v == 1 and labels["path"] == 'a"b\\c\nd'


def test_label_escaping_edge_cases_round_trip():
    """The pathological label values the ISSUE-5 satellite pins: a
    literal backslash followed by ``n`` (the old chained-replace parser
    turned the escaped backslash's tail into a newline), values ending
    in quotes/backslashes, lone escapes, and multi-label lines where an
    escaped quote must not terminate the value early."""
    cases = [
        "a\\nb",        # backslash + 'n' — NOT a newline
        "a\nb",         # a real newline
        'quote"end',
        'end"',
        "trail\\",
        "\\",
        '"',
        'mix\\"x\nand"more\\\\',
        "comma,inside",
        "",
    ]
    r = obs.MetricsRegistry()
    c = r.counter("edl_edge_total", "e", ("v", "other"))
    for i, v in enumerate(cases):
        c.inc(i + 1, v=v, other=f'p,"{i}\\')
    parsed = obs.parse_prometheus_text(r.render())
    got = {lv["v"]: (lv["other"], n) for lv, n in parsed["edl_edge_total"]}
    for i, v in enumerate(cases):
        assert v in got, f"case {i}: {v!r} lost in round trip: {sorted(got)}"
        other, n = got[v]
        assert other == f'p,"{i}\\' and n == i + 1, (v, other, n)


def test_empty_histogram_renders_inf_bucket_and_nan_free_percentiles():
    """An empty histogram still exposes its full cumulative schema
    (+Inf bucket, sum, count, all zero) and every percentile surface
    answers 0.0 — never NaN — through both the direct and the parsed
    paths."""
    import math

    r = obs.MetricsRegistry()
    h = r.histogram("edl_empty_seconds", "empty", buckets=(0.1, 1.0))
    text = r.render()
    assert 'edl_empty_seconds_bucket{le="+Inf"} 0' in text
    assert "edl_empty_seconds_sum 0" in text
    assert "edl_empty_seconds_count 0" in text
    for q in (0.5, 0.95, 0.99):
        direct = h.percentile(q)
        assert direct == 0.0 and not math.isnan(direct)
    parsed = obs.parse_prometheus_text(text)
    for q in (0.5, 0.95, 0.99):
        v = percentile_from_buckets(parsed["edl_empty_seconds_bucket"], q)
        assert v == 0.0 and not math.isnan(v)
    # no bucket samples at all (the degenerate consumer input)
    assert percentile_from_buckets([], 0.99) == 0.0
    # +Inf-only observations clamp to the largest finite edge
    h.observe(50.0)
    assert h.percentile(0.5) == 1.0
    parsed = obs.parse_prometheus_text(r.render())
    assert percentile_from_buckets(
        parsed["edl_empty_seconds_bucket"], 0.5
    ) == 1.0


def test_parse_and_percentile_round_trip():
    r = obs.MetricsRegistry()
    h = r.histogram("edl_rt_seconds", "rt")
    for v in (0.002, 0.004, 0.02, 0.3, 2.0):
        h.observe(v)
    parsed = obs.parse_prometheus_text(r.render())
    for q in (0.5, 0.95, 0.99):
        assert percentile_from_buckets(
            parsed["edl_rt_seconds_bucket"], q
        ) == pytest.approx(h.percentile(q))


def test_core_series_catalog_always_renders():
    """A scrape of any edl process shows the full unlabeled schema
    zero-valued before any observation (the acceptance criterion's
    'training, serving, and reshard series present')."""
    r = obs.ensure_core_series(obs.MetricsRegistry())
    text = r.render()
    for name in (
        "edl_train_step_seconds_count 0",
        "edl_serving_ttft_seconds_count 0",
        "edl_serving_queue_depth 0",
        "edl_reshard_stall_seconds_count 0",
        "# TYPE edl_serving_dispatch_total counter",
        "# TYPE edl_reshard_total counter",
    ):
        assert name in text, name


# ---------------------------------------------------------------------------
# snapshot / merge (fleet aggregation)


def _worker_snapshot(ttft: float, tokens: int) -> str:
    r = obs.MetricsRegistry()
    r.counter("edl_serving_tokens_total", "t").inc(tokens)
    r.histogram("edl_serving_ttft_seconds", "t").observe(ttft)
    r.gauge("edl_serving_queue_depth", "q").set(1)
    return r.snapshot_json()


def test_snapshot_merge_labels_by_worker():
    agg = obs.aggregate_snapshots(
        {"w0": _worker_snapshot(0.02, 10), "w1": _worker_snapshot(0.2, 30)}
    )
    text = agg.render()
    assert 'edl_serving_tokens_total{worker="w0"} 10' in text
    assert 'edl_serving_tokens_total{worker="w1"} 30' in text
    # fleet percentile sums buckets across the worker label
    parsed = obs.parse_prometheus_text(text)
    p99 = percentile_from_buckets(parsed["edl_serving_ttft_seconds_bucket"], 0.99)
    assert 0.1 < p99 <= 0.25  # the slow worker's bucket dominates the tail
    assert agg.gauge("edl_fleet_reporting_workers", "").value() == 0  # not set here


def test_aggregate_skips_corrupt_snapshot():
    agg = obs.aggregate_snapshots(
        {"good": _worker_snapshot(0.01, 5), "bad": "{not json"}
    )
    assert 'worker="good"' in agg.render()


def test_metrics_pusher_publishes_and_final_push():
    seen = []
    reg = obs.MetricsRegistry()
    reg.counter("edl_p_total", "p").inc(4)
    p = obs.MetricsPusher(seen.append, interval_s=3600, registry=reg)
    assert p.push_once()
    p.stop(final_push=True)
    assert len(seen) == 2
    snap = json.loads(seen[-1])
    fam = next(f for f in snap["families"] if f["name"] == "edl_p_total")
    assert fam["samples"][0]["value"] == 4


def test_collect_fleet_aggregates_member_and_extra_snapshots():
    """The coordinator-side scrape pass: live members' pushed
    snapshots + reserved non-member sources (dist_service), labeled
    per worker, counted in edl_fleet_reporting_workers."""
    from edl_tpu.runtime.coordinator import PyCoordinator

    c = PyCoordinator()
    c.register("w0", 1)
    c.register("w1", 1)
    c.kv_put(obs.metrics_key("job", "w0"), _worker_snapshot(0.01, 5))
    c.kv_put(obs.metrics_key("job", "w1"), _worker_snapshot(0.02, 7))
    svc = obs.MetricsRegistry()
    svc.gauge("edl_dist_service_up", "up", ("epoch",)).set(1, epoch="3")
    c.kv_put(obs.metrics_key("job", "dist_service"), svc.snapshot_json())
    reg = obs.collect_fleet(c, "job", ("dist_service",))
    text = reg.render()
    assert 'edl_serving_tokens_total{worker="w0"} 5' in text
    assert 'edl_serving_tokens_total{worker="w1"} 7' in text
    assert 'edl_dist_service_up{epoch="3",worker="dist_service"} 1' in text
    assert "edl_fleet_reporting_workers 3" in text
    # a member with no pushed snapshot yet just doesn't report
    c.register("w2", 1)
    reg = obs.collect_fleet(c, "job")
    assert "edl_fleet_reporting_workers 2" in reg.render()


def test_pusher_survives_failing_publish():
    def boom(_):
        raise ConnectionError("down")

    p = obs.MetricsPusher(boom, interval_s=3600)
    assert p.push_once() is False  # swallowed, telemetry never raises


def test_pusher_backoff_state_lock_guarded_under_contention():
    """push_once runs on the pusher thread AND from stop()'s last-gasp
    call while next_wait_s polls the streak — the backoff state is
    lock-guarded (`edl check` lockset-race finding). Hammer failing
    pushes from many threads: every increment must land (unlocked
    `+= 1` loses updates under bytecode interleaving), and one success
    must reset the streak for every observer."""
    import threading

    fail = {"on": True}

    def pub(_):
        if fail["on"]:
            raise ConnectionError("down")

    reg = obs.MetricsRegistry()
    p = obs.MetricsPusher(pub, interval_s=1.0, backoff_cap_s=64.0, registry=reg)
    n_threads, n_pushes = 8, 50
    start = threading.Barrier(n_threads)

    def hammer():
        start.wait()
        for _ in range(n_pushes):
            p.push_once()
            assert p.next_wait_s() >= 0.5  # jitter floor of the backoff

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert p._fail_streak == n_threads * n_pushes  # no lost increments
    fail["on"] = False
    assert p.push_once() is True
    assert p._fail_streak == 0
    assert p.next_wait_s() == 1.0  # healthy cadence restored


# ---------------------------------------------------------------------------
# live exporter scrape


def test_exporter_live_scrape_metrics_trace_healthz():
    reg = obs.MetricsRegistry()
    reg.counter("edl_live_total", "live").inc(5)
    tr = tracing.Tracer(max_spans=2)
    with tr.span("phase.one"):
        pass
    tr.record("x", 0.0, 0.1)
    tr.record("y", 0.0, 0.1)  # evicts phase.one -> dropped=1
    with obs.MetricsExporter(reg, port=0, tracer=tr) as exp:
        url = exp.url
        # /metrics: valid exposition with the core catalog + our series
        req = urllib.request.urlopen(f"{url}/metrics", timeout=5)
        assert req.status == 200
        assert "text/plain" in req.headers["Content-Type"]
        text = req.read().decode()
        assert "edl_live_total 5" in text
        assert "edl_serving_ttft_seconds_bucket" in text  # core catalog
        assert "edl_reshard_stall_seconds_count" in text
        parsed = obs.parse_prometheus_text(text)
        assert parsed["edl_live_total"] == [({}, 5.0)]
        # /trace: chrome-trace JSON with ring-buffer metadata
        doc = json.loads(obs.scrape(exp.url, "/trace"))
        assert doc["dropped"] == 1
        names = {e["name"] for e in doc["traceEvents"]}
        assert "y" in names and "edl_tracer" in names
        # /healthz
        hz = json.loads(obs.scrape(exp.url, "/healthz"))
        assert hz["status"] == "ok" and hz["uptime_s"] >= 0
        # unknown path -> 404
        with pytest.raises(urllib.error.HTTPError):
            obs.scrape(exp.url, "/nope")
    # server is down after stop
    with pytest.raises(OSError):
        obs.scrape(url, "/metrics", timeout_s=1)


def test_exporter_callable_source_reevaluates_per_scrape():
    calls = []

    def collect():
        r = obs.MetricsRegistry()
        calls.append(1)
        r.gauge("edl_n_scrapes", "n").set(len(calls))
        return r

    with obs.MetricsExporter(collect, port=0) as exp:
        assert "edl_n_scrapes 1" in obs.scrape(exp.url)
        assert "edl_n_scrapes 2" in obs.scrape(exp.url)


# ---------------------------------------------------------------------------
# tracer -> histogram bridge


def test_bridge_tracer_observes_spans_as_histograms():
    reg = obs.MetricsRegistry()
    tr = tracing.Tracer()
    listener = obs.bridge_tracer(reg, tr)
    try:
        with tr.span("reshard"):
            pass
        tr.record("checkpoint.save_shards", 0.0, 0.25)
        h = reg.get("edl_span_seconds")
        assert h.stats(name="reshard")["count"] == 1
        assert h.stats(name="checkpoint.save_shards")["sum"] == pytest.approx(0.25)
        text = reg.render()
        assert 'edl_span_seconds_bucket{name="reshard",le=' in text
    finally:
        tr.remove_listener(listener)


# ---------------------------------------------------------------------------
# monitor-source round trips (StoreSource / ServingSource -> registry)


class _FakeStore:
    """Duck-typed JobStore: the StoreSource contract, no disk."""

    def read_cluster(self):
        return {
            "cpu_total_milli": 8000,
            "cpu_request_milli": 2000,
            "chip_total": 16,
            "chip_request": 8,
        }

    def list_keys(self):
        return [("default", "ctr")]

    def list_statuses(self):
        return {
            ("default", "ctr"): {
                "running": 3,
                "pending": 0,
                "parallelism": 4,
                "phase": "running",
                "reshard_count": 2,
                "last_reshard_stall_s": 1.25,
                "reshard_fallbacks": 1,
            }
        }


def test_store_source_snapshot_round_trip():
    sample = StoreSource(_FakeStore()).sample()
    reg = obs.registry_from_sample(sample)
    parsed = obs.parse_prometheus_text(reg.render())
    assert parsed["edl_fleet_chip_total"] == [({}, 16.0)]
    assert parsed["edl_fleet_chip_util_pct"] == [({}, 50.0)]
    (labels, v), = parsed["edl_job_workers"]
    assert labels == {"job": "ctr"} and v == 3
    (_, stall), = parsed["edl_job_last_reshard_stall_seconds"]
    assert stall == 1.25
    (_, resh), = parsed["edl_job_reshards"]
    assert resh == 2


def test_serving_source_snapshot_round_trip():
    from edl_tpu.serving.metrics import ServingMetrics

    t = [0.0]
    m = ServingMetrics(
        clock=lambda: t[0], registry=obs.MetricsRegistry()
    )
    m.on_submit("r1")
    t[0] = 0.5
    m.on_admit("r1", 4)
    m.on_token("r1")
    t[0] = 0.6
    m.on_tokens("r1", 4)
    m.on_step(1, 8, 2)
    sample = ServingSource(m).sample()
    reg = obs.registry_from_sample(sample)
    parsed = obs.parse_prometheus_text(reg.render())
    by_key = {
        lv["key"]: v for lv, v in parsed["edl_serving_snapshot"]
    }
    # every snapshot scalar round-trips through the registry exactly
    for k, v in m.snapshot().items():
        assert by_key[k] == pytest.approx(v), k
    assert by_key["queue_depth"] == 2
    assert by_key["tokens_out"] == 5
    assert by_key["ttft_p50_s"] > 0


def test_worker_telemetry_exporter_and_push(monkeypatch):
    """ElasticWorker telemetry bring-up: EDL_METRICS_PORT starts the
    exporter and advertises the bound address in coordinator KV;
    metrics_push_s pushes snapshots to {job}/metrics/{worker}; stop
    does a final push."""
    from edl_tpu.runtime.coordinator import (
        CoordinatorServer,
        ensure_native_built,
    )

    if not ensure_native_built():
        pytest.skip("no C++ toolchain")
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        for k, v in {
            "EDL_JOB_NAME": "tj", "EDL_WORKER_ID": "w0",
            "EDL_COORDINATOR": f"127.0.0.1:{srv.port}",
            "EDL_METRICS_PORT": "0", "EDL_METRICS_PUSH_S": "30",
        }.items():
            monkeypatch.setenv(k, v)
        from edl_tpu.runtime.worker_config import WorkerConfig
        from edl_tpu.runtime.worker_main import ElasticWorker

        cfg = WorkerConfig.from_env()
        assert cfg.metrics_port == 0 and cfg.metrics_push_s == 30
        w = ElasticWorker(cfg)
        try:
            w._telemetry_start()
            addr = w.client.kv_get("tj/metrics_addr/w0")
            assert addr and addr.startswith("127.0.0.1:")
            text = obs.scrape(addr)
            assert "edl_train_step_seconds_count" in text
            assert "edl_serving_ttft_seconds_count" in text
        finally:
            w._telemetry_stop()
        snap = w.client.kv_get(obs.metrics_key("tj", "w0"))
        assert snap and "edl_train_steps_total" in snap  # final push
        w.client.close()


def test_monitor_sample_to_record_is_jsonable():
    s = MonitorSample(
        ts=1.0,
        submitted_jobs=["j"],
        running_workers={"j": 2},
        chip_total=8,
        chip_request=4,
        serving={"tokens_out": 3.0},
    )
    rec = json.loads(json.dumps(s.to_record()))
    assert rec["chip_util"] == 50.0
    assert rec["running_workers"] == {"j": 2}
    assert rec["serving"]["tokens_out"] == 3.0


# ---------------------------------------------------------------------------
# serving percentiles surface in the collector render


def test_serving_lines_render_percentiles():
    from edl_tpu.serving.metrics import ServingMetrics

    t = [0.0]
    m = ServingMetrics(clock=lambda: t[0], registry=obs.MetricsRegistry())
    m.on_submit("a")
    t[0] = 0.03
    m.on_admit("a", 2)
    m.on_token("a")
    sample = ServingSource(m).sample()
    text = sample.render()
    assert "latency: ttft p50/p95/p99=" in text
    assert "itl p50/p95/p99=" in text
    # ttft ~30ms lands in the (0.025, 0.05] bucket
    assert 0.025 <= m.snapshot()["ttft_p50_s"] <= 0.05
