"""Sharded checkpoint format: shard-local snapshots, multi-file
manifests, and cross-world-size restore.

The multi-process analog of test_checkpoint_roundtrip: state written as
per-process shard files + manifest must restore onto meshes of OTHER
sizes with each process touching only its local bytes (VERDICT r1 #2;
reference analog surpassed: trainer-0 full save,
example/ctr/ctr/train.py:169-180).
"""

import os

import jax
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.models import ctr
from edl_tpu.parallel import sharding as shd
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.train.trainer import TrainState, shard_state, state_pspecs


def _make_state(plan, mesh, vocab=4096, emb=8):
    params = ctr.init_params(jax.random.PRNGKey(0), vocab=vocab, emb=emb)
    tx = optax.adam(1e-2)
    state = TrainState.create(params, tx)
    return shard_state(state, plan, mesh), tx


def _shardings(state, plan, mesh):
    return TrainState(
        step=NamedSharding(mesh, P()),
        params=shd.named(state_pspecs(state, plan).params, mesh),
        opt_state=shd.named(state_pspecs(state, plan).opt_state, mesh),
    )


def test_snapshot_local_bounds_and_completeness(cpu_devices):
    plan = MeshPlan.fsdp_only(4)
    mesh = plan.build(cpu_devices[:4])
    state, _ = _make_state(plan, mesh)
    snap = ckpt.snapshot_local(state)
    # fsdp=4: embedding pieces are quarter-slices, all primary
    emb = snap.pieces["p:embedding"]
    assert len(emb) == 4
    assert all(p.shape == (1024, 8) for _, p in emb)
    assert snap.primary["p:embedding"] == [o for o, _ in emb]
    # single process holds everything
    assert snap.is_complete()


def test_sharded_roundtrip_across_world_sizes(tmp_path, cpu_devices):
    """Write at fsdp=4, restore at fsdp=2 and fsdp=8: values identical."""
    plan4 = MeshPlan.fsdp_only(4)
    mesh4 = plan4.build(cpu_devices[:4])
    state, tx = _make_state(plan4, mesh4)
    truth = shd.to_host(state.params)

    snap = ckpt.snapshot_local(state)
    root = str(tmp_path / "ck")
    fname = ckpt.save_shards(root, snap, rank=0, world=1, host_leaves=True)
    ckpt.write_manifest(root, snap, [fname], {"job": "t"})

    like = jax.eval_shape(
        lambda: TrainState.create(
            ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), tx
        )
    )
    for n in (2, 8):
        plan = MeshPlan.fsdp_only(n)
        mesh = plan.build(cpu_devices[:n])
        loaded = ckpt.load_sharded(root, like, _shardings(like, plan, mesh))
        got = shd.to_host(loaded.params)
        jax.tree_util.tree_map(np.testing.assert_array_equal, truth, got)
        assert int(loaded.step) == snap.step


def test_multi_writer_files_assemble(tmp_path, cpu_devices):
    """Pieces split across multiple shard files (as distinct ranks
    write them) assemble into one state."""
    plan = MeshPlan.fsdp_only(4)
    mesh = plan.build(cpu_devices[:4])
    state, tx = _make_state(plan, mesh)
    truth = shd.to_host(state.params)
    snap = ckpt.snapshot_local(state)

    # fake two ranks: each owns alternating primary pieces
    def half(i):
        s = ckpt.LocalSnapshot(
            step=snap.step,
            pieces={
                k: [p for j, p in enumerate(v) if j % 2 == i]
                for k, v in snap.pieces.items()
            },
            primary={
                k: [o for j, o in enumerate(v) if j % 2 == i]
                for k, v in snap.primary.items()
            },
            shapes=snap.shapes,
            dtypes=snap.dtypes,
            host_only=snap.host_only,
        )
        return s

    root = str(tmp_path / "ck")
    f0 = ckpt.save_shards(root, half(0), rank=0, world=2, host_leaves=True)
    f1 = ckpt.save_shards(root, half(1), rank=1, world=2)
    ckpt.write_manifest(root, snap, [f0, f1])

    like = jax.eval_shape(
        lambda: TrainState.create(
            ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), tx
        )
    )
    plan2 = MeshPlan.fsdp_only(8)
    mesh2 = plan2.build(cpu_devices[:8])
    loaded = ckpt.load_sharded(root, like, _shardings(like, plan2, mesh2))
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, truth, shd.to_host(loaded.params)
    )


def test_ram_pieces_win_over_disk(tmp_path, cpu_devices):
    """When the RAM snapshot matches the manifest step its pieces are
    used; a stale RAM snapshot is ignored in favor of disk."""
    plan = MeshPlan.fsdp_only(4)
    mesh = plan.build(cpu_devices[:4])
    state, tx = _make_state(plan, mesh)
    snap = ckpt.snapshot_local(state)
    root = str(tmp_path / "ck")
    # DISK copy is poisoned (all zeros); RAM snapshot holds the truth.
    zeroed = ckpt.LocalSnapshot(
        step=snap.step,
        pieces={
            k: [(o, np.zeros_like(a)) for o, a in v]
            for k, v in snap.pieces.items()
        },
        primary=snap.primary,
        shapes=snap.shapes,
        dtypes=snap.dtypes,
        host_only=snap.host_only,
    )
    f = ckpt.save_shards(root, zeroed, 0, 1, host_leaves=True)
    ckpt.write_manifest(root, zeroed, [f])

    like = jax.eval_shape(
        lambda: TrainState.create(
            ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), tx
        )
    )
    sh = _shardings(like, plan, mesh)

    # matching step: RAM pieces must win over the poisoned disk bytes
    loaded = ckpt.load_sharded(root, like, sh, ram=snap)
    jax.tree_util.tree_map(
        np.testing.assert_array_equal,
        shd.to_host(state.params),
        shd.to_host(loaded.params),
    )

    # stale RAM (wrong step) is dropped: the manifest'd disk bytes (all
    # zeros here) are the agreed truth
    stale = ckpt.LocalSnapshot(
        step=snap.step + 7,
        pieces=snap.pieces,
        primary=snap.primary,
        shapes=snap.shapes,
        dtypes=snap.dtypes,
    )
    loaded2 = ckpt.load_sharded(root, like, sh, ram=stale)
    for leaf in jax.tree_util.tree_leaves(shd.to_host(loaded2.params)):
        assert not np.any(leaf)


def test_manifest_commit_protocol(tmp_path, cpu_devices):
    """A step dir without manifest.json is invisible; gc keeps the
    newest checkpoints and reaps aborted dirs."""
    plan = MeshPlan.fsdp_only(2)
    mesh = plan.build(cpu_devices[:2])
    state, _ = _make_state(plan, mesh)
    root = str(tmp_path / "ck")

    snap = ckpt.snapshot_local(state)
    assert ckpt.latest_manifest(root) is None
    f = ckpt.save_shards(root, snap, 0, 1, host_leaves=True)
    # shards written but not committed: still invisible
    assert ckpt.latest_manifest(root) is None
    ckpt.write_manifest(root, snap, [f])
    m = ckpt.latest_manifest(root)
    assert m is not None and m["step"] == snap.step

    # later steps; an aborted (manifest-less) dir in between
    for st in (5, 9):
        s2 = ckpt.LocalSnapshot(
            step=st,
            pieces=snap.pieces,
            primary=snap.primary,
            shapes=snap.shapes,
            dtypes=snap.dtypes,
            host_only=snap.host_only,
        )
        f2 = ckpt.save_shards(root, s2, 0, 1, host_leaves=True)
        if st != 5:  # step 5 aborted: no manifest
            ckpt.write_manifest(root, s2, [f2])
    assert ckpt.latest_manifest(root)["step"] == 9

    ckpt.gc_step_dirs(root, keep=1)
    dirs = sorted(os.listdir(root))
    assert dirs == ["step-00000009"]


def test_incomplete_coverage_raises(tmp_path, cpu_devices):
    plan = MeshPlan.fsdp_only(4)
    mesh = plan.build(cpu_devices[:4])
    state, tx = _make_state(plan, mesh)
    snap = ckpt.snapshot_local(state)
    # drop one primary piece of the embedding before writing
    snap.pieces["p:embedding"] = snap.pieces["p:embedding"][1:]
    snap.primary["p:embedding"] = snap.primary["p:embedding"][1:]
    root = str(tmp_path / "ck")
    f = ckpt.save_shards(root, snap, 0, 1, host_leaves=True)
    ckpt.write_manifest(root, snap, [f])
    like = jax.eval_shape(
        lambda: TrainState.create(
            ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), tx
        )
    )
    with pytest.raises(ValueError, match="coverage incomplete"):
        ckpt.load_sharded(root, like, _shardings(like, plan, mesh))
