"""Flight recorder + postmortem (edl_tpu/obs/events.py,
edl_tpu/obs/postmortem.py).

The observability contract ISSUE 5 pins: a thread-safe bounded ring of
typed, monotonically-sequenced, correlated events; JSONL dump/load;
Perfetto merge; crash dumps to EDL_BLACKBOX_DIR; the /events endpoint
with filters; the KVLogger warn/error bridge; fleet event collection;
and the `edl postmortem` analyzer — including the acceptance chain
``fault_injected -> recover -> re-prefill -> finish`` over a real
engine crash.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import pytest

from edl_tpu import obs
from edl_tpu.obs import events as flight
from edl_tpu.obs import postmortem as pm
from edl_tpu.utils import faults
from edl_tpu.utils.logging import kv_logger


# ---------------------------------------------------------------------------
# recorder semantics


def test_recorder_seq_ring_and_counts():
    rec = flight.FlightRecorder(max_events=4, clock=lambda: 42.0)
    for i in range(6):
        rec.emit("k.a" if i % 2 == 0 else "k.b", rid=f"r{i}", n=i)
    # bounded ring: newest 4 retained, 2 dropped-oldest, seq monotonic
    assert len(rec) == 4 and rec.dropped == 2
    seqs = [e.seq for e in rec.events()]
    assert seqs == sorted(seqs) and seqs[-1] == 6
    assert {e.corr["rid"] for e in rec.events()} == {"r2", "r3", "r4", "r5"}
    # per-kind totals survive eviction
    assert rec.counts() == {"k.a": 3, "k.b": 3}
    # filters
    assert len(rec.events(kind="k.a")) == 2  # r2, r4 retained
    assert rec.events(rid="r5")[0].attrs["n"] == 5
    rec.clear()
    assert len(rec) == 0 and rec.dropped == 0 and rec.counts() == {}


def test_recorder_context_and_severity():
    rec = flight.FlightRecorder()
    rec.set_context(worker="w3")
    e = rec.emit("x", severity="warn", rid="a")
    assert e.corr == {"worker": "w3", "rid": "a"}
    rec.set_context(worker=None)  # clears
    assert rec.emit("y").corr == {}
    with pytest.raises(ValueError):
        rec.emit("z", severity="fatal")


def test_recorder_registry_counters():
    reg = obs.default_registry()
    fam = reg.counter("edl_events_total", "flight-recorder events by kind",
                      ("kind",))
    before = fam.value(kind="test.kind")
    small = flight.FlightRecorder(max_events=1)
    small.emit("test.kind")
    small.emit("test.kind")  # evicts -> dropped counter too
    assert fam.value(kind="test.kind") == before + 2
    assert reg.counter("edl_events_dropped_total", "").value() >= 1


def test_recorder_thread_safety_and_bounded_allocation():
    rec = flight.FlightRecorder(max_events=512)

    def work(t):
        for i in range(1000):
            rec.emit("race", rid=f"t{t}", n=i)

    ts = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # every emit counted exactly once; ring stayed bounded
    assert rec.counts()["race"] == 4000
    assert len(rec) == 512 and rec.dropped == 4000 - 512
    seqs = [e.seq for e in rec.events()]
    assert seqs == sorted(seqs)


def test_emit_overhead_is_steady_state_cheap():
    """The acceptance bound: an emit is one lock + deque append +
    counter inc — comfortably under 1% of even a tiny CPU-dryrun block
    (~ms). Generous ceiling so CI boxes never flake."""
    rec = flight.FlightRecorder(max_events=1024)
    rec.emit("warmup")
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        rec.emit("bench", rid="r", n=i)
    per = (time.perf_counter() - t0) / n
    assert per < 200e-6, f"emit cost {per * 1e6:.1f}us"


# ---------------------------------------------------------------------------
# JSONL round trip + chrome merge


def test_jsonl_dump_load_round_trip(tmp_path):
    rec = flight.FlightRecorder(max_events=3)
    for i in range(5):
        rec.emit("k", rid=f"r{i}", n=i)
    path = rec.dump(str(tmp_path / "flight.jsonl"))
    loaded = flight.load_jsonl(path)
    assert [e["corr"]["rid"] for e in loaded] == ["r2", "r3", "r4"]
    assert loaded[0]["attrs"]["_ring_dropped"] == 2  # meta surfaced
    assert all(e["kind"] == "k" for e in loaded)
    # torn tail tolerated (a crash dump may be cut mid-line)
    torn = open(path).read()[:-20]
    assert len(flight.load_jsonl(torn)) >= 1
    with pytest.raises(ValueError):
        flight.load_jsonl("not json at all")


def test_chrome_doc_merges_instant_events_with_spans():
    from edl_tpu.utils import tracing

    tr = tracing.Tracer()
    with tr.span("phase.one"):
        pass
    rec = flight.FlightRecorder()
    rec.emit("decision.a", rid="r1")
    doc = rec.to_chrome_doc(tr)
    by_ph = {}
    for e in doc["traceEvents"]:
        by_ph.setdefault(e["ph"], []).append(e["name"])
    assert "phase.one" in by_ph["X"]  # span survived
    assert "decision.a" in by_ph["i"]  # event merged as instant
    inst = next(e for e in doc["traceEvents"] if e["ph"] == "i")
    assert inst["args"]["rid"] == "r1" and inst["ts"] >= 0
    assert doc["eventsDropped"] == 0


# ---------------------------------------------------------------------------
# crash dump black box


def test_crash_dump_writes_blackbox(tmp_path, monkeypatch):
    monkeypatch.delenv("EDL_BLACKBOX_DIR", raising=False)
    assert flight.crash_dump("unit") is None  # unset -> no-op
    monkeypatch.setenv("EDL_BLACKBOX_DIR", str(tmp_path / "bb"))
    rec = flight.default_recorder()
    rec.emit("before.crash", rid="r9")
    path = flight.crash_dump("unit", RuntimeError("boom"))
    assert path and os.path.exists(path)
    loaded = flight.load_jsonl(path)
    kinds = [e["kind"] for e in loaded]
    assert "before.crash" in kinds
    crash = next(e for e in loaded if e["kind"] == "blackbox.crash")
    assert crash["severity"] == "error"
    assert "boom" in crash["attrs"]["error"]


# ---------------------------------------------------------------------------
# KVLogger bridge


def test_kvlogger_warn_error_mirror_into_recorder():
    rec = flight.default_recorder()
    rec.clear()
    log = kv_logger("bridge_test")
    log.info("quiet", a=1)  # info is NOT mirrored
    log.warn("warned", rid="r1", detail="x")
    log.error("errored", code=7)
    kinds = rec.counts()
    assert "log.warn" in kinds and "log.error" in kinds
    assert "log.info" not in kinds
    w = rec.events(kind="log.warn")[0]
    assert w.severity == "warn" and w.attrs["msg"] == "warned"
    assert w.corr["rid"] == "r1"  # correlation keys routed to corr
    assert w.attrs["detail"] == "x"
    e = rec.events(kind="log.error")[0]
    assert e.severity == "error" and e.attrs["code"] == 7


# ---------------------------------------------------------------------------
# /events endpoint


def test_exporter_events_endpoint_with_filters():
    rec = flight.default_recorder()
    rec.clear()
    rec.emit("e.one", rid="a")
    rec.emit("e.two", rid="b", severity="warn")
    rec.emit("e.one", rid="b")
    with obs.MetricsExporter(obs.MetricsRegistry(), port=0) as exp:
        raw = obs.scrape(exp.url, "/events")
        recs = [json.loads(l) for l in raw.strip().splitlines()]
        assert [r["kind"] for r in recs] == ["e.one", "e.two", "e.one"]
        rid_b = obs.scrape(exp.url, "/events?rid=b")
        assert all(
            json.loads(l)["corr"]["rid"] == "b"
            for l in rid_b.strip().splitlines()
        )
        one = obs.scrape(exp.url, "/events?kind=e.one&n=1")
        (only,) = [json.loads(l) for l in one.strip().splitlines()]
        assert only["kind"] == "e.one" and only["corr"]["rid"] == "b"
        # /healthz advertises the endpoint
        hz = json.loads(obs.scrape(exp.url, "/healthz"))
        assert "/events" in hz["endpoints"]
        # /trace carries the merged instant events
        doc = json.loads(obs.scrape(exp.url, "/trace"))
        assert {"e.one", "e.two"} <= {
            e["name"] for e in doc["traceEvents"] if e["ph"] == "i"
        }


# ---------------------------------------------------------------------------
# fleet event collection (worker-labeled union through coordinator KV)


def test_metrics_pusher_publishes_events_window():
    snaps, windows = [], []
    rec = flight.FlightRecorder()
    rec.emit("w.k", rid="r1")
    p = obs.MetricsPusher(
        snaps.append, interval_s=3600, registry=obs.MetricsRegistry(),
        events_publish=windows.append, events_window=16, recorder=rec,
    )
    assert p.push_once()
    assert len(snaps) == 1 and len(windows) == 1
    # KV is a line protocol: the pushed window must be ONE line
    assert "\n" not in windows[0]
    (rec0,) = flight.load_jsonl(windows[0])
    assert rec0["kind"] == "w.k"


def test_collect_fleet_events_labels_by_worker():
    from edl_tpu.runtime.coordinator import PyCoordinator

    c = PyCoordinator()
    c.register("w0", 1)
    c.register("w1", 1)
    r0 = flight.FlightRecorder(clock=lambda: 1.0)
    r0.emit("a.k", rid="x")
    r1 = flight.FlightRecorder(clock=lambda: 2.0)
    r1.set_context(worker="w1-self")  # a stamped context wins
    r1.emit("b.k")
    c.kv_put(obs.events_key("job", "w0"), r0.window_json())
    c.kv_put(obs.events_key("job", "w1"), r1.window_json())
    c.kv_put(obs.events_key("job", "w2"), "{torn")  # skipped, not fatal
    c.register("w2", 1)
    merged = obs.collect_fleet_events(c, "job")
    assert [(r["kind"], r["corr"]["worker"]) for r in merged] == [
        ("a.k", "w0"), ("b.k", "w1-self"),
    ]


# ---------------------------------------------------------------------------
# postmortem analyzer (synthetic timelines)


def _ev(seq, t, kind, severity="info", corr=None, attrs=None):
    return {
        "seq": seq, "t_wall": t, "t_mono": t, "kind": kind,
        "severity": severity, "corr": corr or {}, "attrs": attrs or {},
    }


def _chain_events(broken=None):
    evs = [
        _ev(1, 0.0, "serve.submit", corr={"rid": "r1"}),
        _ev(2, 0.1, "serve.prefill", corr={"rid": "r1"}),
        _ev(3, 0.1, "serve.admit", corr={"rid": "r1"}),
        _ev(4, 0.2, "fault.injected", "warn", {"site": "serve.dispatch"},
            {"nth": 3, "action": "raise"}),
        _ev(5, 0.3, "serve.recover", "warn", {},
            {"rids": ["r1"], "requeued": None, "error": "InjectedFault"}),
        _ev(6, 0.4, "serve.prefill", corr={"rid": "r1"},
            attrs={"replay": True}),
        _ev(7, 0.5, "serve.finish", corr={"rid": "r1"},
            attrs={"outcome": "done", "tokens": 4}),
    ]
    if broken == "no_recover":
        evs = [e for e in evs if e["kind"] != "serve.recover"]
    elif broken == "no_replay":
        evs = [e for e in evs if not (e["attrs"] or {}).get("replay")]
    elif broken == "bad_outcome":
        evs[-1]["attrs"]["outcome"] = "failed"
    return evs


def test_verify_recovered_accepts_complete_chain():
    assert pm.verify_recovered(_chain_events()) == []
    chains = pm.fault_chains(_chain_events())
    assert len(chains) == 1 and chains[0]["ok"]
    assert chains[0]["rids"][0] == {
        "rid": "r1", "replayed": True, "outcome": "done"
    }


@pytest.mark.parametrize("broken", ["no_recover", "no_replay", "bad_outcome"])
def test_verify_recovered_flags_broken_chains(broken):
    problems = pm.verify_recovered(_chain_events(broken))
    assert problems, broken


def test_verify_recovered_requires_faults():
    # a chaos dump whose faults never fired tested nothing
    assert pm.verify_recovered([_ev(1, 0.0, "serve.submit")]) != []


def test_verify_no_incidents():
    clean = [
        _ev(1, 0.0, "serve.submit", corr={"rid": "r"}),
        _ev(2, 0.1, "serve.finish", corr={"rid": "r"},
            attrs={"outcome": "done"}),
    ]
    assert pm.verify_no_incidents(clean) == []
    assert pm.verify_no_incidents(_chain_events())  # fault + recovery
    shed = clean + [_ev(3, 0.2, "serve.reject", "warn", {"rid": "s"},
                        {"reason": "timeout", "shed": True})]
    assert any("timeout" in p for p in pm.verify_no_incidents(shed))
    err = clean + [_ev(4, 0.3, "log.error", "error", {}, {"msg": "bad"})]
    assert any("error" in p for p in pm.verify_no_incidents(err))


def test_render_report_timelines_and_gaps():
    out = pm.render_report(_chain_events())
    assert "fault -> recovery chains" in out and "[OK]" in out
    assert "request r1" in out and "serve.finish" in out
    # the reshard summary line
    resh = [_ev(1, 0.0, "reshard.end", corr={"reshard_epoch": 0},
                attrs={"from_workers": 2, "to_workers": 4,
                       "stall_s": 1.5, "path": "device"})]
    out2 = pm.render_report(resh)
    assert "reshard_epoch=0" in out2 and "stall=1.5s" in out2


def test_incidents_attach_follow_window():
    inc = pm.incidents(_chain_events(), window_s=10.0)
    (f,) = inc["faults"]
    followed = [e["kind"] for e in f["followed"]]
    assert "serve.recover" in followed and "serve.finish" in followed
    assert len(inc["recoveries"]) == 1


# ---------------------------------------------------------------------------
# the acceptance chain over a REAL engine crash + the CLI verb


def _env():
    return {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
    }


def test_engine_crash_chain_and_postmortem_cli(tmp_path):
    """End to end: injected dispatch fault -> engine recovery, the
    flight recorder holds the causal chain, the analyzer verifies it
    in-process, AND the `edl postmortem` CLI verifies the dumped file
    (the run_tests.sh phase-6 contract)."""
    from edl_tpu.models import llama
    from edl_tpu.serving.engine import ContinuousBatchingEngine

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rec = flight.default_recorder()
    rec.clear()
    faults.arm("serve.dispatch:raise@n=2", seed=0)
    try:
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=2, max_len=64, horizon=4
        )
        eng.submit("x", [1, 2, 3], 8)
        eng.submit("y", [4, 5, 6], 7)
        res = eng.run()
    finally:
        faults.disarm()
    assert eng.recoveries == 1
    assert {r.outcome for r in res.values()} <= {"done", "eos"}
    recs = rec.records()
    assert pm.verify_recovered(recs) == []
    (chain,) = pm.fault_chains(recs)
    assert {r["rid"] for r in chain["rids"]} == {"x", "y"}
    # every replayed rid shows a replay prefill between recover and finish
    for rid in ("x", "y"):
        kinds = [e["kind"] for e in recs
                 if (e.get("corr") or {}).get("rid") == rid]
        assert kinds.index("serve.finish") > kinds.index("serve.prefill")

    dump = str(tmp_path / "chain.jsonl")
    rec.dump(dump)
    out = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "postmortem", dump,
         "--assert-recovered"],
        capture_output=True, text=True, env=_env(),
    )
    assert out.returncode == 0, out.stderr
    assert "postmortem assertions OK" in out.stdout
    assert "fault -> recovery chains" in out.stdout and "[OK]" in out.stdout
    # the same dump fails the no-incidents gate (it has a fault)
    bad = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "postmortem", dump,
         "--assert-no-incidents"],
        capture_output=True, text=True, env=_env(),
    )
    assert bad.returncode == 1 and "POSTMORTEM FAIL" in bad.stderr
    # unreadable source -> exit 2
    miss = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "postmortem",
         str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, env=_env(),
    )
    assert miss.returncode == 2


def test_recover_writes_blackbox_dump(tmp_path, monkeypatch):
    """The engine's _recover is a black box: EDL_BLACKBOX_DIR gets the
    ring BEFORE the rebuild, and the dump itself passes postmortem."""
    from edl_tpu.models import llama
    from edl_tpu.serving.engine import ContinuousBatchingEngine

    bb = tmp_path / "bb"
    monkeypatch.setenv("EDL_BLACKBOX_DIR", str(bb))
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    flight.default_recorder().clear()
    faults.arm("serve.drain:raise@n=1", seed=0)
    try:
        eng = ContinuousBatchingEngine(params, cfg, max_slots=1, max_len=32)
        eng.submit("a", [1, 2], 4)
        res = eng.run()
    finally:
        faults.disarm()
    assert res["a"].outcome == "done"
    dumps = sorted(bb.glob("blackbox-serving-*.jsonl"))
    assert dumps, "no black-box dump written"
    loaded = flight.load_jsonl(str(dumps[0]))
    kinds = [e["kind"] for e in loaded]
    assert "fault.injected" in kinds and "serve.recover" in kinds


def test_postmortem_loads_from_live_events_url():
    rec = flight.default_recorder()
    rec.clear()
    rec.emit("live.k", rid="u1")
    with obs.MetricsExporter(obs.MetricsRegistry(), port=0) as exp:
        evs = pm.load_events(f"{exp.url}")
        assert [e["kind"] for e in evs] == ["live.k"]
        # a pasted .../events URL (what the exporter actually serves)
        # must load too, with filters passed through to the endpoint
        assert [e["kind"] for e in pm.load_events(f"{exp.url}/events")] == [
            "live.k"
        ]
        # a filter that matches nothing keeps the empty-input guard:
        # better a loud error than a silently empty postmortem
        with pytest.raises(ValueError):
            pm.load_events(f"{exp.url}/events?rid=nope")


# ---------------------------------------------------------------------------
# edl top incident strip (satellite)


def test_top_incident_strip_from_event_counters():
    from edl_tpu.obs.top import summarize

    r = obs.MetricsRegistry()
    # quiet endpoint: no strip
    assert not any("INCIDENT" in l for l in summarize(
        obs.parse_prometheus_text(r.render())
    ))
    r.counter("edl_serving_recoveries_total", "").inc(2)
    r.counter("edl_faults_injected_total", "", ("site",)).inc(
        3, site="serve.dispatch"
    )
    r.gauge("edl_worker_heartbeat_degraded", "").set(1)
    r.counter("edl_events_dropped_total", "").inc(7)
    r.counter("edl_events_total", "", ("kind",)).inc(4, kind="log.error")
    fams = obs.parse_prometheus_text(r.render())
    (strip,) = [l for l in summarize(fams) if l.startswith("INCIDENT")]
    assert "recoveries=2" in strip
    assert "faults_injected=3" in strip
    assert "hb_degraded=1" in strip
    assert "log_errors=4" in strip
    assert "dropped_events=7" in strip
