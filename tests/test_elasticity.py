"""Chip-lease broker + elasticity controller state machine.

The broker tests pin the lease lifecycle jax-free (this module imports
no jax at top level — the policy plane must stay importable on a
device-free control node); the weightpush roundtrip imports jax inside
the test. The ``lease.recall`` chaos site is armed here to prove the
controller's recall retry closes the postmortem fault chain.
"""

import threading

import pytest

from edl_tpu.elasticity.broker import (
    FREED,
    GRANTED,
    RECALLING,
    ChipLeaseBroker,
    LeaseError,
)
from edl_tpu.elasticity.controller import (
    ElasticityController,
    ServePort,
    TrainPort,
)
from edl_tpu.obs import events as flight
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.scheduler.autoscaler import ScaleGate
from edl_tpu.utils import faults


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _broker(chips: int = 8, clock=None) -> ChipLeaseBroker:
    return ChipLeaseBroker(
        chips, registry=MetricsRegistry(), clock=clock or Clock()
    )


# ---------------------------------------------------------------------------
# broker state machine


def test_lease_lifecycle_and_conservation():
    b = _broker(8)
    a = b.grant("train:job", 6)
    assert a.state == GRANTED and a.chips == 6
    assert b.free_chips == 2
    assert b.check_conservation()

    r = b.recall(a.lease_id)
    assert r.state == RECALLING
    assert b.free_chips == 2  # chips stay with the holder until free
    assert b.check_conservation()

    assert b.free(a.lease_id) == 6
    assert b.get(a.lease_id).state == FREED
    assert b.free_chips == 8
    assert b.check_conservation()


def test_epoch_monotonic_across_grants():
    b = _broker(8)
    epochs = []
    for i in range(3):
        lease = b.grant(f"serve:r{i}", 2)
        epochs.append(lease.epoch)
        b.recall(lease.lease_id)
        b.free(lease.lease_id)
    more = b.grant("train:job", 8)
    epochs.append(more.epoch)
    assert epochs == sorted(epochs)
    assert len(set(epochs)) == len(epochs)  # strictly increasing
    assert b.epoch == epochs[-1]


def test_double_grant_rejected():
    b = _broker(4)
    b.grant("train:job", 3)
    with pytest.raises(LeaseError, match="only 1/4"):
        b.grant("serve:r0", 2)  # pool can't cover it
    assert b.free_chips == 1
    assert b.check_conservation()


def test_recall_while_recalling_is_idempotent():
    flight.reset_default_recorder()
    b = _broker(4)
    lease = b.grant("train:job", 4)
    first = b.recall(lease.lease_id)
    again = b.recall(lease.lease_id)  # retried recall RPC: no-op
    assert first.state == again.state == RECALLING
    assert first.recalled_t == again.recalled_t
    # exactly one lease.recall event despite two calls
    evs = [e for e in flight.default_recorder().records()
           if e["kind"] == "lease.recall"
           and e["attrs"].get("lease") == lease.lease_id]
    assert len(evs) == 1


def test_free_requires_recall_first():
    b = _broker(4)
    lease = b.grant("train:job", 2)
    with pytest.raises(LeaseError, match="not RECALLING"):
        b.free(lease.lease_id)
    b.recall(lease.lease_id)
    assert b.free(lease.lease_id) == 2
    assert b.free(lease.lease_id) == 0  # idempotent repeat
    with pytest.raises(LeaseError, match="already FREED"):
        b.recall(lease.lease_id)


def test_unknown_lease_raises():
    b = _broker(2)
    with pytest.raises(LeaseError, match="unknown"):
        b.recall("L9999")
    with pytest.raises(LeaseError, match="unknown"):
        b.free("L9999")


def test_holder_crash_mid_recalling_returns_chips():
    b = _broker(8)
    stuck = b.grant("serve:r0", 2)
    held = b.grant("serve:r0", 2)
    other = b.grant("train:job", 4)
    b.recall(stuck.lease_id)  # recall sent, ack never comes
    dead = b.holder_crashed("serve:r0")
    assert sorted(l.lease_id for l in dead) == sorted(
        [stuck.lease_id, held.lease_id]
    )
    assert all(l.state == FREED for l in dead)
    assert b.free_chips == 4  # both leases back in the pool
    assert b.check_conservation()
    assert b.get(other.lease_id).state == GRANTED  # bystander untouched
    assert b.holder_crashed("serve:r0") == []  # settled: second call no-op


def test_broker_thread_safety_smoke():
    # the deterministic-scheduler proof lives in `edl schedcheck
    # lease-broker`; this is the plain-threads sanity pass
    b = _broker(16)

    def churn(side: str) -> None:
        for i in range(25):
            try:
                lease = b.grant(f"{side}:h{i}", 1)
            except LeaseError:
                continue
            b.recall(lease.lease_id)
            b.free(lease.lease_id)

    ts = [threading.Thread(target=churn, args=(s,))
          for s in ("train", "serve", "train")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert b.free_chips == 16
    assert b.check_conservation()


# ---------------------------------------------------------------------------
# the shared ScaleGate


def test_scale_gate_damps_and_records():
    clk = Clock()
    gate = ScaleGate("k", 30.0, clock=clk)
    acts = []
    out = gate.apply(lambda: "up", acts.append)
    assert out == "up" and acts == ["up"]
    # inside the cooldown: held
    clk.t = 10.0
    assert gate.apply(lambda: "up", acts.append) is None
    assert acts == ["up"]
    # cooldown elapsed
    clk.t = 31.0
    assert gate.apply(lambda: "down", acts.append) == "down"
    assert acts == ["up", "down"]


def test_scale_gate_bypass_and_none_decision():
    clk = Clock()
    urgent = {"on": False}
    gate = ScaleGate("k", 60.0, clock=clk, bypass=lambda: urgent["on"])
    acts = []
    assert gate.apply(lambda: None, acts.append) is None  # nothing to do
    assert gate.apply(lambda: "up", acts.append) == "up"
    assert gate.apply(lambda: "up", acts.append) is None  # cooled down
    urgent["on"] = True
    assert gate.apply(lambda: "up", acts.append) == "up"  # bypass wins
    assert acts == ["up", "up"]


# ---------------------------------------------------------------------------
# controller policy loop (fake ports, fake clock)


class FakeSides:
    """Train + serve stand-ins sharing one mutable state doc."""

    def __init__(self, train_chips: int = 6, replicas: int = 1,
                 chips_per_replica: int = 2):
        self.train_chips = train_chips
        self.replicas = replicas
        self.offered = 0.0
        self.breach = False
        self.ramps = 0

    def train_port(self) -> TrainPort:
        return TrainPort(
            chips=lambda: self.train_chips,
            apply_chips=lambda n: setattr(self, "train_chips", n),
            min_chips=2,
        )

    def serve_port(self) -> ServePort:
        def add() -> float:
            self.replicas += 1
            self.ramps += 1
            return 0.5

        def rm() -> None:
            self.replicas -= 1

        return ServePort(
            replicas=lambda: self.replicas,
            load=lambda: self.offered / max(self.replicas, 1),
            slo_breached=lambda: self.breach,
            add_replica=add,
            remove_replica=rm,
            min_replicas=1,
        )


def _controller(sides: FakeSides, clk: Clock, broker=None,
                **kw) -> ElasticityController:
    broker = broker or _broker(8, clock=clk)
    kw.setdefault("chips_per_replica", 2)
    kw.setdefault("cooldown_s", 0.0)
    ctl = ElasticityController(
        broker, sides.train_port(), sides.serve_port(),
        clock=clk, registry=MetricsRegistry(), **kw
    )
    ctl.bootstrap()
    return ctl


def test_controller_full_diurnal_cycle():
    clk = Clock()
    sides = FakeSides(train_chips=6, replicas=1)
    ctl = _controller(sides, clk)
    broker = ctl.broker
    assert broker.free_chips == 0  # bootstrap leased everything

    sides.offered = 12.0  # day: load per replica = 12 > 4
    assert ctl.tick() == "to_serve"
    assert sides.train_chips == 4 and sides.replicas == 2
    assert broker.check_conservation()

    sides.offered = 0.4  # night: load 0.2 < 0.5
    assert ctl.tick() == "to_train"
    assert sides.train_chips == 6 and sides.replicas == 1
    assert broker.check_conservation()
    assert [h.direction for h in ctl.ledger] == ["to_serve", "to_train"]
    assert ctl.ledger[0].ramp_s == 0.5
    # epochs on the ledger are strictly increasing
    assert ctl.ledger[0].epoch < ctl.ledger[1].epoch


def test_controller_respects_floors():
    clk = Clock()
    sides = FakeSides(train_chips=4, replicas=2)
    ctl = _controller(sides, clk)
    # train floor: shedding a replica's worth would leave 2 == min_chips,
    # still allowed; one more would go below — two ticks, second held
    sides.offered = 100.0
    assert ctl.tick() == "to_serve"
    assert sides.train_chips == 2
    assert ctl.tick() is None  # floor: trainer can't shrink further
    # serve floor
    sides.offered = 0.0
    ctl.tick()
    ctl.tick()
    ctl.tick()
    assert sides.replicas == 1  # min_replicas holds
    assert ctl.tick() is None


def test_controller_cooldown_and_slo_bypass():
    clk = Clock()
    sides = FakeSides(train_chips=8, replicas=1)
    ctl = _controller(sides, clk, broker=_broker(10, clock=clk),
                      cooldown_s=300.0)
    sides.offered = 50.0
    assert ctl.tick() == "to_serve"
    assert ctl.tick() is None  # cooled down
    sides.breach = True  # SLO breach bypasses the cooldown
    assert ctl.tick() == "to_serve"
    assert sides.replicas == 3


def test_recall_retry_emits_lease_recover():
    flight.reset_default_recorder()
    clk = Clock()
    sides = FakeSides(train_chips=6, replicas=1)
    ctl = _controller(sides, clk)
    faults.arm("lease.recall:raise@n=1")
    try:
        sides.offered = 12.0
        assert ctl.tick() == "to_serve"  # retry inside the handover
        assert faults.counts().get("lease.recall") == 1
    finally:
        faults.disarm()
    evs = flight.default_recorder().records()
    kinds = [e["kind"] for e in evs]
    assert "fault.injected" in kinds
    assert "lease.recover" in kinds
    rec = next(e for e in evs if e["kind"] == "lease.recover")
    assert rec["corr"]["site"] == "lease.recall"
    assert rec["attrs"]["rids"] == []
    assert ctl.ledger[-1].recall_retries == 1
    # the dump closes the fault chain under the lease. site prefix
    from edl_tpu.obs import postmortem as pm

    problems = pm.verify_recovered(evs, site_prefix="lease.")
    assert problems == [], problems


def test_recall_retries_exhausted_raises():
    from edl_tpu.elasticity.controller import LeaseRecallFailed

    clk = Clock()
    sides = FakeSides(train_chips=6, replicas=1)
    ctl = _controller(sides, clk, recall_retries=1)
    faults.arm("lease.recall:raise@every=1")
    try:
        sides.offered = 12.0
        with pytest.raises(LeaseRecallFailed):
            ctl.tick()
    finally:
        faults.disarm()
    # the failed recall never moved state: the lease is still live and
    # conservation holds
    assert ctl.broker.check_conservation()
    assert sides.train_chips == 6 and sides.replicas == 1


# ---------------------------------------------------------------------------
# p2p weight push (jax inside the test: the wire plane needs arrays)


def test_weightpush_roundtrip(cpu_devices):
    import numpy as np

    from edl_tpu.elasticity import weightpush
    from edl_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(vocab=64)
    import jax

    params = llama.init_params(jax.random.PRNGKey(7), cfg)
    srv = weightpush.serve_params(params, cfg.to_meta(), step=123)
    try:
        got, doc, step = weightpush.fetch_params(f"127.0.0.1:{srv.port}")
    finally:
        srv.close()
    assert step == 123
    assert doc == cfg.to_meta()
    from edl_tpu.runtime.checkpoint import _leaf_keys

    flat_in = {k: np.asarray(v) for k, v in _leaf_keys(params)}
    flat_out = {k: np.asarray(v) for k, v in _leaf_keys(got)}
    assert set(flat_out.keys()) == set(flat_in.keys())
    for k, v in flat_in.items():
        np.testing.assert_array_equal(flat_out[k], v)


def test_weightpush_dead_peer_raises():
    from edl_tpu.elasticity import weightpush

    with pytest.raises(ConnectionError):
        weightpush.fetch_params("127.0.0.1:1", timeout_s=0.2)


# ---------------------------------------------------------------------------
# CLI rehearsal verb


def test_cli_elasticity_json(capsys):
    import json

    from edl_tpu.cli.main import main

    rc = main(["elasticity", "--hours", "48", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["conserved"] is True
    dirs = [h["direction"] for h in doc["handovers"]]
    # two full day/night cycles in 48 scripted hours
    assert dirs.count("to_serve") >= 2 and dirs.count("to_train") >= 2
