"""`edl check` static analysis (edl_tpu/analysis/): per-rule fixture
snippets (true positive, clean negative, suppressed), the baseline
round-trip, the CLI verb, and the self-check that the shipped codebase
is clean against its committed baseline. jax-free — the analyzer is
pure stdlib-ast."""

import json
import os
import textwrap

import pytest

from edl_tpu import analysis
from edl_tpu.cli.main import main as cli_main


def run_on(tmp_path, source, rules=None, name="mod.py", extra=None):
    """Analyze one fixture module (plus optional sibling files) rooted
    at tmp_path; returns the Report."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    for rel, text in (extra or {}).items():
        q = tmp_path / rel
        q.parent.mkdir(parents=True, exist_ok=True)
        q.write_text(textwrap.dedent(text))
    return analysis.run_check([str(p)], rules=rules, root=str(tmp_path))


def rules_of(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# donation-safety


DONATED_DEF = """
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, x):
        return state + x
"""


def test_donation_read_after_donate_is_flagged(tmp_path):
    rep = run_on(tmp_path, DONATED_DEF + """
    def loop(state, xs):
        total = 0.0
        for x in xs:
            new = step(state, x)
            total += float(state.sum())  # stale read of the donated buffer
            state = new
        return total
    """, rules=["donation-safety"])
    assert rules_of(rep) == ["donation-safety"]
    assert "'state' is read after being donated to step" in rep.findings[0].message
    assert rep.findings[0].severity == "error"


def test_donation_rebind_is_clean(tmp_path):
    rep = run_on(tmp_path, DONATED_DEF + """
    def loop(state, xs):
        for x in xs:
            state = step(state, x)  # rebound: the blessed pattern
        return state
    """, rules=["donation-safety"])
    assert rep.findings == []


def test_donation_factory_and_self_attr_pattern(tmp_path):
    """The engine shape: a factory whose nested def carries the
    donation, bound to self.X, called with subscripted tuple args —
    reading the tuple afterwards is the PR 2 stale-buffer bug."""
    rep = run_on(tmp_path, """
    from functools import partial
    import jax

    def _program(cfg):
        def make():
            @partial(jax.jit, donate_argnums=(1, 2))
            def run(params, kc, vc):
                return kc, vc
            return run
        return make()

    class Engine:
        def __init__(self, cfg):
            self._decode = _program(cfg)

        def dispatch(self):
            old = (self._kc, self._vc)
            self._kc, self._vc = self._decode(self.params, old[0], old[1])
            return old[0].sum()  # stale read through the tuple
    """, rules=["donation-safety"])
    assert rules_of(rep) == ["donation-safety"]
    assert "'old'" in rep.findings[0].message


def test_donation_suppression(tmp_path):
    rep = run_on(tmp_path, DONATED_DEF + """
    def probe(state, x):
        new = step(state, x)
        # edl: no-lint[donation-safety] deliberate is_deleted probe
        assert state.is_deleted()
        return new
    """, rules=["donation-safety"])
    assert rep.findings == []
    assert rep.suppressed == 1


def test_donation_tuple_unpack_through_helper(tmp_path):
    """One-level call summary: `a, b = split(buf)` donates buf even
    though the jit call is inside the helper (the satellite-task false
    negative — previously invisible to the per-function dataflow)."""
    rep = run_on(tmp_path, DONATED_DEF + """
    def split(buf, x):
        a = step(buf, x)
        return a, x

    def use(buf, x):
        a, b = split(buf, x)
        return float(buf.sum())  # stale: buf was donated inside split
    """, rules=["donation-safety"])
    assert rules_of(rep) == ["donation-safety"]
    assert "'buf' is read after being donated to split" in rep.findings[0].message


def test_donation_helper_negatives_are_clean(tmp_path):
    """No summary for a helper that doesn't donate, or that rebinds
    the parameter before the donating call (the donated value is the
    callee's own, not the caller's)."""
    rep = run_on(tmp_path, DONATED_DEF + """
    def noop(buf, x):
        return buf + x  # no donation inside

    def shield(buf, x):
        buf = buf + 0.0  # rebound: callee donates its own copy
        return step(buf, x)

    def use(buf, x):
        y = noop(buf, x)
        z = shield(buf, x)
        return float(buf.sum())
    """, rules=["donation-safety"])
    assert rep.findings == []


def test_donation_helper_method_level(tmp_path):
    """`self._advance(state)` donates through one method-call level;
    rebinding from the helper's result stays the blessed pattern."""
    rep = run_on(tmp_path, DONATED_DEF + """
    class Engine:
        def _advance(self, state, x):
            return step(state, x)

        def run(self, state, xs):
            for x in xs:
                state = self._advance(state, x)  # rebound: clean
            return state

        def bad(self, state, x):
            out = self._advance(state, x)
            return float(state.sum())  # stale read through the helper
    """, rules=["donation-safety"])
    assert rules_of(rep) == ["donation-safety"]
    f = rep.findings[0]
    assert "'state' is read after being donated to self._advance" in f.message


# ---------------------------------------------------------------------------
# lockset-race


def test_lockset_cross_context_no_lock_is_flagged(tmp_path):
    rep = run_on(tmp_path, """
    import threading

    class Pusher:
        def __init__(self):
            self._streak = 0

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while True:
                self.push_once()

        def push_once(self):
            self._streak += 1

        def stop(self):
            self.push_once()  # main thread touches the same state
    """, rules=["lockset-race"])
    assert rules_of(rep) == ["lockset-race"]
    assert "Pusher._streak" in rep.findings[0].message


def test_lockset_mixed_guard_is_flagged_and_common_lock_is_clean(tmp_path):
    flagged = run_on(tmp_path, """
    import threading

    class Conn:
        def __init__(self):
            self.lock = threading.Lock()
            self.sock = None

        def use(self):
            with self.lock:
                return self.sock

        def close(self):
            self.sock = None  # unguarded write
    """, rules=["lockset-race"])
    assert rules_of(flagged) == ["lockset-race"]
    assert "mixed locking" in flagged.findings[0].message

    clean = run_on(tmp_path, """
    import threading

    class Conn:
        def __init__(self):
            self.lock = threading.Lock()
            self.sock = None

        def use(self):
            with self.lock:
                return self.sock

        def close(self):
            with self.lock:
                self.sock = None
    """, rules=["lockset-race"], name="clean.py")
    assert clean.findings == []


def test_lockset_locked_suffix_convention(tmp_path):
    """Methods named *_locked are assumed called with the lock held —
    the documented convention for internal helpers."""
    rep = run_on(tmp_path, """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._todo = []

        def get(self):
            with self._lock:
                self._reap_locked()
                return self._todo.pop()

        def _reap_locked(self):
            self._todo.append(1)
    """, rules=["lockset-race"])
    assert rep.findings == []


def test_lockset_init_and_readonly_are_exempt(tmp_path):
    rep = run_on(tmp_path, """
    import threading

    class Server:
        def __init__(self):
            self._cfg = {"a": 1}   # written only at construction
            threading.Thread(target=self._loop, daemon=True).start()

        def _loop(self):
            while True:
                self.handle()

        def handle(self):
            return self._cfg["a"]  # read-only after init: safe
    """, rules=["lockset-race"])
    assert rep.findings == []


def test_lockset_acquire_release_statements_guard(tmp_path):
    """Bare self._lock.acquire()/try/finally-release() counts as a
    guarded region, same as `with self._lock` (previously invisible:
    the accesses in between looked unguarded and produced a spurious
    mixed-lockset finding)."""
    rep = run_on(tmp_path, """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self._lock.acquire()
            try:
                self.n += 1
            finally:
                self._lock.release()

        def bump(self):
            with self._lock:
                self.n += 1
    """, rules=["lockset-race"])
    assert rep.findings == []


def test_lockset_rlock_reentrant_nested_helper_is_clean(tmp_path):
    """The satellite-task fixture: a nested helper defined under the
    RLock runs under it (def-site lockset inheritance) — re-entry in
    the helper is NOT a fresh unguarded access."""
    rep = run_on(tmp_path, """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.RLock()
            self.total = 0
            threading.Thread(target=self.loop, daemon=True).start()

        def loop(self):
            with self._lock:
                def add(v):
                    self.total += v  # runs under the outer RLock
                add(1)
                self._lock.acquire()  # re-entrant acquire, same lock
                try:
                    add(2)
                finally:
                    self._lock.release()

        def read(self):
            with self._lock:
                return self.total
    """, rules=["lockset-race"])
    assert rep.findings == []


def test_lockset_nested_thread_target_does_not_inherit(tmp_path):
    """The counterweight to def-site inheritance: a nested def handed
    to Thread(target=...) runs in the NEW thread, where nothing is
    held — it must stay unguarded and flag."""
    rep = run_on(tmp_path, """
    import threading

    class Spawner:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def start(self):
            with self._lock:
                def work():
                    self.n += 1  # new thread: the lock is NOT held
                threading.Thread(target=work, daemon=True).start()

        def read(self):
            with self._lock:
                return self.n
    """, rules=["lockset-race"])
    assert rules_of(rep) == ["lockset-race"]
    assert "Spawner.n" in rep.findings[0].message


def test_lockset_private_helper_inherits_caller_lock(tmp_path):
    """One-level interprocedural context: a private helper invoked
    only under the lock is guarded; add one bare caller and the race
    is visible again."""
    clean = run_on(tmp_path, """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self.loop, daemon=True).start()

        def _bump(self):
            self.n += 1  # only ever called under the lock

        def loop(self):
            with self._lock:
                self._bump()

        def read(self):
            with self._lock:
                return self.n
    """, rules=["lockset-race"])
    assert clean.findings == []

    mixed = run_on(tmp_path, """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self.loop, daemon=True).start()

        def _bump(self):
            self.n += 1

        def loop(self):
            with self._lock:
                self._bump()

        def poke(self):
            self._bump()  # bare public caller: the race is back

        def read(self):
            with self._lock:
                return self.n
    """, rules=["lockset-race"], name="mixed.py")
    assert rules_of(mixed) == ["lockset-race"]
    assert "Counter.n" in mixed.findings[0].message


# ---------------------------------------------------------------------------
# recompile-hazard


def test_recompile_per_call_jit_flagged_memo_clean(tmp_path):
    flagged = run_on(tmp_path, """
    import jax

    def predict(params, rows):
        fwd = jax.jit(lambda p, x: p @ x)  # fresh wrapper per call
        return [fwd(params, r) for r in rows]
    """, rules=["recompile-hazard"])
    assert rules_of(flagged) == ["recompile-hazard"]
    assert "fresh wrapper per call" in flagged.findings[0].message

    clean = run_on(tmp_path, """
    import jax

    _cache = {}

    def predict(params, rows):
        fn = _cache.get("fwd")
        if fn is None:
            fn = jax.jit(lambda p, x: p @ x)  # built once behind the guard
            _cache["fwd"] = fn
        return [fn(params, r) for r in rows]
    """, rules=["recompile-hazard"], name="clean.py")
    assert clean.findings == []


def test_recompile_host_sync_inside_jit(tmp_path):
    rep = run_on(tmp_path, """
    import jax
    import numpy as np

    @jax.jit
    def bad(x):
        return float(x) + np.asarray(x).sum() + x.mean().item()
    """, rules=["recompile-hazard"])
    msgs = " | ".join(f.message for f in rep.findings)
    assert ".item() inside jitted" in msgs
    assert "float() coercion" in msgs
    assert "np.asarray() on a traced value" in msgs


def test_recompile_shape_branch_and_validation_exemption(tmp_path):
    rep = run_on(tmp_path, """
    import jax

    @jax.jit
    def f(x):
        if x.shape[0] > 4:   # recompiles per shape class
            x = x * 2
        if x.shape[1] != 8:  # trace-time validation: exempt
            raise ValueError("bad width")
        return x
    """, rules=["recompile-hazard"])
    assert len(rep.findings) == 1
    assert "shape-dependent Python branch" in rep.findings[0].message


def test_recompile_unhashable_static_args(tmp_path):
    rep = run_on(tmp_path, """
    from functools import partial
    import jax

    @partial(jax.jit, static_argnums=(1,))
    def f(x, cfg):
        return x

    def call(x):
        return f(x, [1, 2, 3])  # list at a static position: TypeError
    """, rules=["recompile-hazard"])
    assert rules_of(rep) == ["recompile-hazard"]
    assert "unhashable literal" in rep.findings[0].message
    assert rep.findings[0].severity == "error"


# ---------------------------------------------------------------------------
# silent-failure


def test_silent_failure_flagged_and_handled_variants_clean(tmp_path):
    rep = run_on(tmp_path, """
    def swallow():
        try:
            work()
        except Exception:
            pass
    """, rules=["silent-failure"])
    assert rules_of(rep) == ["silent-failure"]

    clean = run_on(tmp_path, """
    def loud(log, errs, counter):
        try:
            work()
        except Exception as e:
            log.warn("work failed", error=str(e))
        try:
            work()
        except Exception as e:
            errs.append(e)       # exception object flows onward
        try:
            work()
        except Exception:
            counter.inc()        # counted = visible
        try:
            work()
        except Exception:
            raise
        try:
            work()
        except OSError:
            pass                 # narrow catch: a stated decision
    """, rules=["silent-failure"], name="clean.py")
    assert clean.findings == []


def test_silent_failure_suppression_counted(tmp_path):
    rep = run_on(tmp_path, """
    def teardown():
        try:
            close()
        # edl: no-lint[silent-failure] best-effort teardown
        except Exception:
            pass
    """, rules=["silent-failure"])
    assert rep.findings == [] and rep.suppressed == 1


# ---------------------------------------------------------------------------
# telemetry-conventions


def test_telemetry_metric_name_and_event_kind(tmp_path):
    rep = run_on(tmp_path, """
    def instrument(reg, events):
        reg.counter("requests_total", "no prefix")
        reg.gauge("edl_ok_gauge", "fine")
        events.emit("recovered", rid="r1")     # not site.verb
        events.emit("serve.recover", rid="r1") # fine
    """, rules=["telemetry-conventions"])
    msgs = " | ".join(f.message for f in rep.findings)
    assert "'requests_total' does not follow" in msgs
    assert "event kind 'recovered'" in msgs
    assert len(rep.findings) == 2


def test_telemetry_suffix_kind_conventions(tmp_path):
    """Counters must end _total; nothing else may; _ratio/_fraction
    must be gauges (the hardware-efficiency families' convention)."""
    rep = run_on(tmp_path, """
    def instrument(reg):
        reg.counter("edl_widgets", "counter without _total")
        reg.gauge("edl_things_total", "gauge posing as a counter")
        reg.histogram("edl_kv_occupancy_ratio", "ratio as histogram")
        reg.counter("edl_ok_total", "fine")
        reg.gauge("edl_bw_util_ratio", "fine", ("phase",))
        reg.gauge("edl_goodput_fraction", "fine")
        reg.histogram("edl_step_seconds", "fine")
    """, rules=["telemetry-conventions"])
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 3, msgs
    assert any("must end '_total'" in m for m in msgs)
    assert any("ends '_total' but is not a counter" in m for m in msgs)
    assert any(
        "ends '_ratio'/'_fraction' but is not a gauge" in m for m in msgs
    )


def test_telemetry_conflicting_registration(tmp_path):
    rep = run_on(tmp_path, """
    def a(reg):
        reg.counter("edl_widgets_total", "as counter")

    def b(reg):
        reg.gauge("edl_widgets_total", "same name, other kind")
    """, rules=["telemetry-conventions"])
    assert any("conflicting schema" in f.message for f in rep.findings)


def test_telemetry_trace_keys_only_via_disttrace(tmp_path):
    """Hand-rolled trace-context key access (subscript, .get, dict
    literal) is flagged everywhere EXCEPT obs/disttrace.py — the
    helpers own the wire format."""
    rep = run_on(tmp_path, """
    def relay(corr, remote):
        corr["trace_id"] = remote.trace_id          # subscript write
        parent = corr.get("parent_id")              # dict-method read
        return {"span_id": parent}                  # dict literal
    """, rules=["telemetry-conventions"])
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 3, msgs
    assert all("obs/disttrace helpers" in m for m in msgs)
    assert any("'trace_id' (subscript)" in m for m in msgs)
    assert any("'parent_id' (.get())" in m for m in msgs)
    assert any("'span_id' (dict literal)" in m for m in msgs)


def test_telemetry_trace_keys_clean_patterns(tmp_path):
    """The sanctioned shapes stay clean: disttrace.py itself, helper
    calls, and attribute access (ctx.trace_id is not a dict key)."""
    home = run_on(tmp_path, """
    def inject(d, ctx):
        d["trace_id"] = ctx.trace_id
        return d.get("span_id")
    """, rules=["telemetry-conventions"], name="disttrace.py",
        extra={"obs/__init__.py": ""})
    # fixture file is named disttrace.py but not under obs/ — still
    # flagged; the real home path is exempt
    assert len(home.findings) == 2
    ok = run_on(tmp_path, """
    from edl_tpu.obs import disttrace

    def relay(corr):
        ctx = disttrace.extract(corr)
        tid = ctx.trace_id if ctx else None
        return disttrace.inject({}, ctx), tid
    """, rules=["telemetry-conventions"])
    assert ok.findings == []


def test_telemetry_trace_keys_exempt_in_disttrace_home(tmp_path):
    p = tmp_path / "obs"
    p.mkdir()
    (p / "disttrace.py").write_text(
        'def inject(d, c):\n    d["trace_id"] = c.trace_id\n    return d\n'
    )
    import edl_tpu.analysis as analysis_mod

    rep = analysis_mod.run_check(
        [str(p / "disttrace.py")],
        rules=["telemetry-conventions"], root=str(tmp_path),
    )
    assert rep.findings == []


def test_telemetry_fault_site_coverage(tmp_path):
    covered = run_on(tmp_path, """
    from edl_tpu.utils import faults

    def lease():
        faults.fault_point("data.lease")

    def push():
        faults.fault_point("obscure.site")
    """, rules=["telemetry-conventions"], extra={
        "tests/test_chaos.py": 'PLAN = "data.lease:raise@n=1"\n',
    })
    assert len(covered.findings) == 1
    assert "'obscure.site' is not referenced" in covered.findings[0].message


def test_telemetry_alert_rules_series_must_exist(tmp_path):
    """A DEFAULT_RULES entry watching a series nothing registers is an
    error — an alert rule over a typo'd name silently never fires."""
    rep = run_on(tmp_path, """
    DEFAULT_RULES = {
        "time_scale": 1.0,
        "rules": [
            {"name": "ok_rule", "type": "threshold",
             "series": "edl_widgets_total", "op": ">", "value": 1.0},
            {"name": "ghost_rule", "type": "threshold",
             "series": "edl_ghost_series", "op": ">", "value": 1.0},
        ],
    }

    def instrument(reg):
        reg.counter("edl_widgets_total", "exists")
    """, rules=["telemetry-conventions"])
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert "ghost_rule" in f.message and "'edl_ghost_series'" in f.message
    assert f.severity == "error"


def test_telemetry_alert_rules_skip_partial_runs(tmp_path):
    """With no registrations in scope (a partial run over one file),
    the series check cannot judge and stays silent."""
    rep = run_on(tmp_path, """
    DEFAULT_RULES = {
        "rules": [
            {"name": "r", "type": "threshold",
             "series": "edl_anything", "op": ">", "value": 1.0},
        ],
    }
    """, rules=["telemetry-conventions"])
    assert rep.findings == []


def test_telemetry_alert_namespace_kinds(tmp_path):
    """Only alert.fire / alert.resolve may live in the alert.* event
    namespace — postmortem's incident chainer pairs exactly those."""
    rep = run_on(tmp_path, """
    def transitions(events):
        events.emit("alert.fired", rule="r")    # wrong spelling
        events.emit("alert.fire", rule="r")     # fine
        events.emit("alert.resolve", rule="r")  # fine
    """, rules=["telemetry-conventions"])
    assert len(rep.findings) == 1
    assert "alert.* namespace" in rep.findings[0].message
    assert "'alert.fired'" in rep.findings[0].message


# ---------------------------------------------------------------------------
# kv-block


def test_kv_block_free_without_table_clear_is_flagged(tmp_path):
    rep = run_on(tmp_path, """
    class Engine:
        def evict(self, i):
            tbl = self._tables[i]
            for j, bid in enumerate(tbl):
                if bid != 0:
                    self._balloc.free(bid)  # table entry never cleared
    """, rules=["kv-block"])
    assert rules_of(rep) == ["kv-block"]
    assert "'bid'" in rep.findings[0].message
    assert "table" in rep.findings[0].message
    assert rep.findings[0].severity == "error"


def test_kv_block_free_with_table_clear_is_clean(tmp_path):
    rep = run_on(tmp_path, """
    SCRATCH = 0

    class Engine:
        def evict(self, i):
            tbl = self._tables[i]
            for j, bid in enumerate(tbl):
                if bid != SCRATCH:
                    self._balloc.free(bid)
                    tbl[j] = SCRATCH

        def cow(self, slot, j):
            tbl = self._tables[slot]
            bid = tbl[j]
            dst = self._balloc.alloc()
            tbl[j] = dst
            self._balloc.free(bid)
    """, rules=["kv-block"])
    assert rep.findings == []


def test_kv_block_non_table_free_is_exempt(tmp_path):
    # the prefix cache freeing its own map entries references no
    # table — refcount-only releases are not the hazard
    rep = run_on(tmp_path, """
    class PrefixCache:
        def evict_one(self):
            for key, bid in self._map.items():
                if self._alloc.refcount(bid) == 1:
                    del self._map[key]
                    self._alloc.free(bid)
                    return True
            return False
    """, rules=["kv-block"])
    assert rep.findings == []


def test_kv_block_suppression(tmp_path):
    rep = run_on(tmp_path, """
    class Engine:
        def drop(self, i):
            tbl = self._tables[i]
            bid = tbl[0]
            # edl: no-lint[kv-block] table discarded wholesale below
            self._balloc.free(bid)
            del self._tables[i]
    """, rules=["kv-block"])
    assert rep.findings == []
    assert rep.suppressed == 1


# ---------------------------------------------------------------------------
# baseline round-trip + framework


def test_baseline_round_trip(tmp_path):
    src = """
    def swallow():
        try:
            work()
        except Exception:
            pass
    """
    rep = run_on(tmp_path, src, rules=["silent-failure"])
    assert len(rep.findings) == 1

    bl = tmp_path / "baseline.json"
    analysis.write_baseline(str(bl), rep.findings)
    rep2 = analysis.run_check(
        [str(tmp_path / "mod.py")], rules=["silent-failure"],
        baseline=str(bl), root=str(tmp_path),
    )
    assert rep2.findings == [] and len(rep2.baselined) == 1
    assert not rep2.failed

    # a SECOND instance of the same pattern exceeds the baseline count
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(src) + textwrap.dedent(src).replace("swallow", "gulp")
    )
    rep3 = analysis.run_check(
        [str(tmp_path / "mod.py")], rules=["silent-failure"],
        baseline=str(bl), root=str(tmp_path),
    )
    assert len(rep3.findings) == 1 and len(rep3.baselined) == 1
    assert rep3.failed


def test_unknown_rule_rejected(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    with pytest.raises(ValueError, match="unknown rule"):
        analysis.run_check([str(tmp_path / "m.py")], rules=["bogus"])


def test_syntax_error_is_reported_not_fatal(tmp_path):
    (tmp_path / "bad.py").write_text("def broken(:\n")
    rep = analysis.run_check([str(tmp_path / "bad.py")], root=str(tmp_path))
    assert rep.failed and rep.errors and "bad.py" in rep.errors[0]


# ---------------------------------------------------------------------------
# CLI verb


def test_cli_check_json_and_exit_codes(tmp_path, capsys):
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent("""
    def swallow():
        try:
            work()
        except Exception:
            pass
    """))
    rc = cli_main(["check", str(mod), "--json", "--root", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["ok"] is False
    assert doc["findings"][0]["rule"] == "silent-failure"

    bl = tmp_path / "bl.json"
    rc = cli_main([
        "check", str(mod), "--root", str(tmp_path),
        "--write-baseline", str(bl),
    ])
    capsys.readouterr()
    assert rc == 0 and bl.exists()
    rc = cli_main([
        "check", str(mod), "--root", str(tmp_path), "--baseline", str(bl),
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "0 findings (1 baselined" in out


# ---------------------------------------------------------------------------
# the self-check: the shipped package is clean against its baseline


def test_repo_is_clean_under_edl_check():
    """THE acceptance gate: `edl check` over edl_tpu/ reports zero
    non-baselined findings (every deliberate violation carries an
    in-code `# edl: no-lint[...]` reason or a baseline entry), and the
    full-package run stays inside the 30 s wall-time budget."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rep = analysis.run_check(
        [os.path.join(root, "edl_tpu")],
        baseline=os.path.join(root, "analysis_baseline.json"),
        root=root,
    )
    assert rep.findings == [], analysis.render_text(rep)
    assert rep.errors == []
    assert rep.files > 80  # the whole package was actually walked
    assert rep.suppressed >= 5  # triaged deliberate sites are counted
    assert rep.duration_s < 30.0
