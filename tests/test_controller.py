"""Controller + updater lifecycle against the FakeCluster.

The integration tests the reference never wrote (SURVEY §4: the fake
clientset was "never used" there); state-machine semantics follow
pkg/updater/trainingJobUpdater.go.
"""

import threading

from edl_tpu.api.job import JobPhase, ResourceState, TrainingJob
from edl_tpu.cluster.fake import FakeCluster, FakeHost
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.updater import JobUpdater


def tpu_fleet(n=4):
    return FakeCluster(hosts=[FakeHost(f"h{i}", 8000, 16000, 4) for i in range(n)])


def make_job(name="j1", lo=2, hi=8, ft=True, chips=4):
    return TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": ft,
                "worker": {
                    "min_replicas": lo,
                    "max_replicas": hi,
                    "resources": {
                        "requests": {"cpu": "500m", "memory": "1Gi", "tpu": chips},
                        "limits": {"tpu": chips},
                    },
                },
            },
        }
    )


def test_lifecycle_to_running():
    c = tpu_fleet()
    job = make_job()
    u = JobUpdater(job, c)
    assert u.phase == JobPhase.NONE
    u.step()  # parse -> creating -> create coordinator (await ready)
    # FakeCluster places the coordinator synchronously, so one more step
    # creates workers and reaches running
    u.step()
    assert u.phase == JobPhase.RUNNING
    assert job.status.master.state == ResourceState.READY
    assert job.status.parallelism == 2
    assert c.job_pods(job) == (2, 2, 0)


def test_validation_failure_goes_failed():
    c = tpu_fleet()
    job = make_job(ft=False, lo=2, hi=8)  # elastic without fault_tolerant
    u = JobUpdater(job, c)
    u.step()
    assert u.phase == JobPhase.FAILED
    assert "fault_tolerant" in job.status.reason


def test_ft_job_survives_partial_failure():
    # reference: FT fails only when ALL workers failed (GetStatus :361-370)
    c = tpu_fleet()
    job = make_job()
    u = JobUpdater(job, c)
    u.step()
    u.step()
    pods = [p for p in c.pods.values() if p.role == "worker"]
    c.kill_pod(pods[0].name)
    u.step()
    assert u.phase == JobPhase.RUNNING
    c.kill_pod(pods[1].name)
    u.step()
    assert u.phase == JobPhase.FAILED
    assert "all workers" in job.status.reason


def test_ft_job_survives_replacement_churn():
    # Cumulative failures must NOT fail a job whose replacements are
    # healthy (the reference's GetStatus compares cumulative Failed ==
    # Parallelism and would false-fail here).
    c = tpu_fleet()
    job = make_job()
    u = JobUpdater(job, c)
    u.step()
    u.step()
    for _ in range(3):  # kill -> replace -> kill the replacement ...
        pods = [
            p
            for p in c.pods.values()
            if p.role == "worker" and p.phase == "running"
        ]
        c.kill_pod(pods[0].name)
        c.reconcile()  # k8s Job controller creates a replacement
        u.step()
        assert u.phase == JobPhase.RUNNING, job.status.reason


def test_non_ft_job_fails_on_any_failure():
    # reference: non-FT fails on ANY worker failure (GetStatus :371-380)
    c = tpu_fleet()
    job = make_job(ft=False, lo=2, hi=2)
    u = JobUpdater(job, c)
    u.step()
    u.step()
    pods = [p for p in c.pods.values() if p.role == "worker"]
    c.kill_pod(pods[0].name)
    u.step()
    assert u.phase == JobPhase.FAILED


def test_success_releases_coordinator():
    c = tpu_fleet()
    job = make_job()
    u = JobUpdater(job, c)
    u.step()
    u.step()
    c.finish_workers("default", "j1-worker", success=True)
    u.step()
    assert u.phase == JobPhase.SUCCEEDED
    # terminal release: coordinator gone (reference: Convert :400-412)
    assert ("default", "j1-coordinator") not in c.coordinators


def test_controller_end_to_end_sync():
    c = tpu_fleet()
    ctl = Controller(c, max_load_desired=1.0)
    job = make_job()
    c.submit_job(job)  # watch fires on_add -> updater created
    ctl.step()
    assert ctl.phase_of("j1") == JobPhase.RUNNING
    # autoscaler grows the job into the idle fleet
    ctl.autoscaler.tick()
    g = c.get_worker_group(job)
    assert g.parallelism == 4
    # scale event surfaced as SCALING phase, then runtime reports done
    assert ctl.phase_of("j1") == JobPhase.SCALING
    ctl.updaters["j1"].on_reshard_done(stall_s=1.5)
    assert ctl.phase_of("j1") == JobPhase.RUNNING
    assert job.status.reshard_count == 1
    assert job.status.last_reshard_stall_s == 1.5
    # deletion drains everything
    c.delete_job("default", "j1")
    assert "j1" not in ctl.updaters
    assert ("default", "j1-worker") not in c.groups


def test_scale_event_reaches_non_default_namespace_updater():
    """Scale listeners must fire with the qualified name: updaters are
    keyed by it, so a bare-name notification would silently miss any
    job outside the default namespace (and alias same-named jobs
    across namespaces)."""
    c = tpu_fleet()
    ctl = Controller(c, max_load_desired=1.0)
    job = make_job()
    job.namespace = "team-a"
    c.submit_job(job)
    ctl.step()
    assert ctl.phase_of("team-a/j1") == JobPhase.RUNNING
    ctl.autoscaler.tick()
    assert c.get_worker_group(job).parallelism == 4
    # the SCALING phase must surface on THIS job's updater
    assert ctl.phase_of("team-a/j1") == JobPhase.SCALING
    assert job.status.reshard_count == 1


def test_controller_threaded_run():
    c = tpu_fleet()
    ctl = Controller(c, max_load_desired=1.0)
    ctl.autoscaler.loop_seconds = 0.05
    ctl.run(updater_interval_s=0.05)
    job = make_job()
    c.submit_job(job)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if (
            ctl.phase_of("j1") in (JobPhase.RUNNING, JobPhase.SCALING)
            and c.get_worker_group(job).parallelism == 4
        ):
            break
        time.sleep(0.05)
    ctl.stop()
    assert c.get_worker_group(job).parallelism == 4


def test_updater_map_threadsafe_under_churn():
    """Watch events (on_add/on_delete) land on the cluster's watch
    thread while the updater ticker iterates on its own — the updaters
    map is lock-guarded (`edl check` lockset-race finding). Churn jobs
    from the event side while step() spins: no lost or resurrected
    updaters, no dict-mutation errors escaping the tick."""
    c = tpu_fleet(n=16)
    ctl = Controller(c, max_load_desired=1.0)
    tick_errors = []
    stop = threading.Event()

    def ticker():
        while not stop.is_set():
            try:
                ctl.step()
            except RuntimeError as e:  # "dict changed size" class
                tick_errors.append(e)

    t = threading.Thread(target=ticker, daemon=True)
    t.start()
    jobs = [make_job(name=f"churn{i}", lo=1, hi=2, chips=4) for i in range(24)]
    try:
        for i, job in enumerate(jobs):
            ctl.on_add(job)
            if i % 2:
                ctl.on_delete(job)
    finally:
        stop.set()
        t.join(5)
    assert not tick_errors
    kept = {f"churn{i}" for i in range(24) if i % 2 == 0}
    assert {u.rsplit("/", 1)[-1] for u in ctl.updaters} == kept
    # duplicate add on the event thread must stay a no-op (the
    # check-then-insert is one atomic section now)
    before = dict(ctl.updaters)
    ctl.on_add(jobs[0])
    assert ctl.updaters == before
