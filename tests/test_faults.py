"""Deterministic fault injection (edl_tpu/utils/faults.py): plan
grammar, trigger semantics, seeded determinism, actions, env/JSON
arming, and the injection counter. jax-free."""

import json
import os
import subprocess
import sys
import time

import pytest

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


# -- grammar -----------------------------------------------------------------


def test_parse_plan_grammar():
    specs = faults.parse_plan(
        "serve.dispatch:raise@n=3;coord.rpc:drop@p=0.05;"
        "metrics.push:delay@every=2,s=0.25,max=4"
    )
    assert [s.site for s in specs] == [
        "serve.dispatch", "coord.rpc", "metrics.push"
    ]
    assert specs[0].action == "raise" and specs[0].n == 3
    assert specs[1].action == "drop" and specs[1].p == 0.05
    assert specs[2].action == "delay"
    assert specs[2].every == 2 and specs[2].delay_s == 0.25 and specs[2].max == 4


@pytest.mark.parametrize("bad", [
    "",                          # empty plan
    "site-without-action",       # no action
    "s:explode@n=1",             # unknown action
    "s:raise@n=1,every=2",       # two triggers
    "s:raise",                   # no trigger
    "s:raise@p=1.5",             # p out of range
    "s:raise@bogus=1",           # unknown param
])
def test_parse_plan_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_plan(bad)


# -- triggers ----------------------------------------------------------------


def _fires(site, calls):
    out = []
    for _ in range(calls):
        try:
            faults.fault_point(site)
            out.append(False)
        except (faults.InjectedFault, faults.InjectedConnectionError):
            out.append(True)
    return out


def test_nth_call_fires_exactly_once():
    faults.arm("s:raise@n=3")
    assert _fires("s", 6) == [False, False, True, False, False, False]
    assert faults.counts() == {"s": 1}


def test_every_k_with_max_cap():
    faults.arm("s:raise@every=2,max=2")
    assert _fires("s", 8) == [False, True, False, True, False, False,
                              False, False]
    assert faults.counts() == {"s": 2}


def test_probability_deterministic_given_seed():
    runs = []
    for _ in range(2):
        faults.arm("s:raise@p=0.3", seed=7)
        runs.append(_fires("s", 40))
    assert runs[0] == runs[1]
    assert any(runs[0]) and not all(runs[0])
    faults.arm("s:raise@p=0.3", seed=8)
    assert _fires("s", 40) != runs[0]  # a different seed, different walk


def test_sites_are_independent_streams():
    """Per-site PRNGs: interleaving calls to another site must not
    perturb a site's firing pattern (determinism survives concurrency
    reordering across sites)."""
    faults.arm("a:raise@p=0.5;b:raise@p=0.5", seed=3)
    solo = _fires("a", 20)
    faults.arm("a:raise@p=0.5;b:raise@p=0.5", seed=3)
    interleaved = []
    for _ in range(20):
        _fires("b", 1)
        interleaved.extend(_fires("a", 1))
    assert interleaved == solo


def test_arm_resets_counters():
    faults.arm("s:raise@n=1")
    assert _fires("s", 1) == [True]
    faults.arm("s:raise@n=1")  # re-arm: the nth-call counter restarts
    assert _fires("s", 1) == [True]


# -- actions -----------------------------------------------------------------


def test_drop_raises_connection_error():
    faults.arm("rpc:drop@n=1")
    with pytest.raises(ConnectionError) as e:
        faults.fault_point("rpc")
    assert isinstance(e.value, faults.InjectedConnectionError)
    assert e.value.site == "rpc"


def test_delay_sleeps():
    faults.arm("slow:delay@n=1,s=0.1")
    t0 = time.perf_counter()
    faults.fault_point("slow")  # injected delay, no raise
    assert time.perf_counter() - t0 >= 0.1
    t0 = time.perf_counter()
    faults.fault_point("slow")  # n=1 passed: no-op again
    assert time.perf_counter() - t0 < 0.05


def test_unarmed_is_noop_and_cheap():
    assert not faults.armed()
    for _ in range(1000):
        faults.fault_point("anything")  # must never raise
    assert faults.counts() == {}


# -- observability -----------------------------------------------------------


def test_injections_counted_in_registry():
    reg = obs_metrics.reset_default_registry()
    faults.arm("x:raise@every=1,max=3")
    _fires("x", 5)
    c = reg.get("edl_faults_injected_total")
    assert c is not None and c.value(site="x") == 3


# -- env / JSON arming -------------------------------------------------------


def test_env_arming_inline_and_json(tmp_path):
    code = (
        "from edl_tpu.utils import faults\n"
        "assert faults.armed()\n"
        "import pytest, sys\n"
        "try:\n"
        "    faults.fault_point('serve.dispatch')\n"
        "except faults.InjectedFault:\n"
        "    sys.exit(0)\n"
        "sys.exit(1)\n"
    )
    env = {**os.environ, "PYTHONPATH": os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))}
    r = subprocess.run(
        [sys.executable, "-c", code],
        env={**env, "EDL_FAULTS": "serve.dispatch:raise@n=1"},
    )
    assert r.returncode == 0

    doc = {"seed": 5, "faults": [
        {"site": "serve.dispatch", "action": "raise", "n": 1}
    ]}
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(doc))
    r = subprocess.run(
        [sys.executable, "-c", code],
        env={**env, "EDL_FAULTS": str(plan_file)},
    )
    assert r.returncode == 0


def test_json_plan_arm_direct():
    specs = faults.arm({"seed": 2, "faults": [
        {"site": "a", "action": "drop", "p": 1.0, "max": 1},
    ]})
    assert len(specs) == 1 and specs[0].p == 1.0
    assert _fires("a", 3) == [True, False, False]


# -- real fault-site coverage ------------------------------------------------


def test_data_lease_site_fires_on_real_path():
    """`data.lease` is declared on ElasticDataQueue.get_task — the
    redelivery path chaos exercises. Arm it here so every declared
    fault site is exercised by at least one test (the `edl check`
    telemetry-conventions coverage gate), and pin that a lost lease
    call is survivable: the task is NOT leased when the fault fires
    before the lease is taken, so a retry hands it out intact."""
    from edl_tpu.runtime.data import ElasticDataQueue

    q = ElasticDataQueue(n_samples=4, chunk_size=2, passes=1)
    faults.arm("data.lease:raise@n=1")
    with pytest.raises(faults.InjectedFault):
        q.get_task("w0")
    # the fault fired BEFORE the lease was taken: nothing leaked
    assert q.progress()["leased"] == 0
    t1 = q.get_task("w0")  # retry succeeds and leases the same work
    assert t1 is not None and t1.start == 0
    assert faults.counts() == {"data.lease": 1}
