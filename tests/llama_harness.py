"""Shared llama parity harness (NOT a test module — safe to import as
``tests.llama_harness`` from any test file without the double-import
footgun of importing one test module from another)."""

import dataclasses

import jax
import numpy as np
import optax

from edl_tpu.models import llama
from edl_tpu.train.trainer import (
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def loss_curve(plan, cfg=None, n_batches=3, **cfg_overrides):
    """Train the tiny llama for a few SGD steps under ``plan`` and
    return the loss curve — the parity harness for every strategy mesh
    (a layout choice must not change the math)."""
    cfg = cfg or llama.LlamaConfig.tiny()
    if cfg_overrides:
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    batches = [
        llama.synthetic_tokens(np.random.RandomState(i), 8, 16, cfg.vocab)
        for i in range(n_batches)
    ]
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    tx = optax.sgd(1e-2)
    pspecs = llama.param_pspecs(cfg, plan)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    step = make_train_step(
        llama.make_loss_fn(cfg, plan, mesh), tx, plan, mesh, pspecs
    )
    out = []
    for b in batches:
        state, m = step(state, global_batch(b, plan, mesh))
        out.append(float(m["loss"]))
    return out
