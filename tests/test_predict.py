"""edl predict — the family-universal serving consumer (VERDICT r4 #2).

The reference's serving artifact is the offline CTR inference model
(/root/reference/example/ctr/ctr/train.py:169-180) scored by a separate
process; here every family's export carries an architecture record and
``predict_batch`` rebuilds config + forward from it alone."""

import subprocess
import sys

import jax
import numpy as np
import pytest

from edl_tpu.runtime import predict as pred
from edl_tpu.runtime.export import export_params


def _ctr_export(tmp_path, vocab=512):
    from edl_tpu.models import ctr

    params = ctr.init_params(jax.random.PRNGKey(0), vocab=vocab, emb=8)
    export_params(
        str(tmp_path), params, step=3, dtype="none",
        model_meta={
            "family": "ctr", "vocab": vocab, "emb": 8,
            "mlp_dims": list(ctr.MLP_DIMS),
        },
    )
    rows = ctr.synthetic_batch(np.random.RandomState(0), 96, vocab=vocab)
    return rows


def test_predict_ctr_prob_and_auc(tmp_path):
    rows = _ctr_export(tmp_path)
    params, doc = pred.load_params_for_predict(str(tmp_path))
    out = pred.predict_batch(params, doc, rows)
    assert out["prob"].shape == (96,)
    assert np.all((out["prob"] >= 0) & (out["prob"] <= 1))
    assert 0.0 <= out["auc"] <= 1.0
    # without labels: no metric, same probs
    out2 = pred.predict_batch(
        params, doc, {k: rows[k] for k in ("dense", "sparse")}
    )
    np.testing.assert_allclose(out2["prob"], out["prob"], rtol=1e-6)
    assert "auc" not in out2


def test_predict_ctr_sharded_mesh(tmp_path, cpu_devices):
    """--mesh path: the generic pspec rule shards a LIST-bearing param
    tree (ctr's mlp stack — the ADVICE r4 spec_for case) and scoring
    matches the host-resident load bit-for-bit."""
    rows = _ctr_export(tmp_path)
    params_h, doc = pred.load_params_for_predict(str(tmp_path))
    params_s, doc_s = pred.load_params_for_predict(str(tmp_path), "fsdp")
    out_h = pred.predict_batch(params_h, doc, rows)
    out_s = pred.predict_batch(params_s, doc_s, rows)
    np.testing.assert_allclose(out_s["prob"], out_h["prob"], rtol=1e-5)
    # the big leaf actually sharded (not replicated fallback)
    emb = params_s["embedding"]
    assert len(emb.sharding.device_set) > 1
    spec = emb.sharding.spec
    assert any(s is not None for s in spec)


def test_predict_resnet(tmp_path):
    from edl_tpu.models import resnet

    cfg = resnet.ResNetConfig.tiny(num_classes=7)
    params = resnet.init_params(jax.random.PRNGKey(1), cfg)
    export_params(
        str(tmp_path), params, step=2, dtype="none",
        model_meta=cfg.to_meta(),
    )
    rows = resnet.synthetic_batch(
        np.random.RandomState(0), 24, size=16, num_classes=7
    )
    params2, doc = pred.load_params_for_predict(str(tmp_path))
    out = pred.predict_batch(params2, doc, rows)
    assert out["class"].shape == (24,)
    assert set(np.unique(out["class"])).issubset(set(range(7)))
    assert 0.0 <= out["acc"] <= 1.0


def test_predict_bert_masked(tmp_path):
    from edl_tpu.models import bert

    cfg = bert.BertConfig.tiny(vocab=128)
    params = bert.init_params(jax.random.PRNGKey(2), cfg)
    export_params(
        str(tmp_path), params, step=5, dtype="none",
        model_meta=cfg.to_meta(),
    )
    rows = bert.synthetic_mlm_batch(np.random.RandomState(0), 16, 12, 128)
    params2, doc = pred.load_params_for_predict(str(tmp_path))
    out = pred.predict_batch(params2, doc, rows)
    assert out["pred"].shape == rows["tokens"].shape
    assert 0.0 <= out["masked_acc"] <= 1.0


@pytest.mark.parametrize("family", ["llama", "moe"])
def test_predict_lm_next_token_and_ppl(tmp_path, family):
    if family == "llama":
        from edl_tpu.models import llama as mod

        cfg = mod.LlamaConfig.tiny(vocab=128)
    else:
        from edl_tpu.models import moe as mod

        cfg = mod.MoEConfig.tiny(vocab=128)
    params = mod.init_params(jax.random.PRNGKey(3), cfg)
    export_params(
        str(tmp_path), params, step=9, dtype="none",
        model_meta=cfg.to_meta(),
    )
    toks = np.random.RandomState(0).randint(0, 128, (8, 10)).astype(np.int32)
    params2, doc = pred.load_params_for_predict(str(tmp_path))
    out = pred.predict_batch(params2, doc, {"tokens": toks})
    assert out["next_token"].shape == (8,)
    assert out["ppl"] > 0


def test_predict_rejects_recordless_export(tmp_path):
    export_params(
        str(tmp_path), {"w": np.ones((2, 2), np.float32)}, step=1,
        dtype="none",
    )
    params, doc = pred.load_params_for_predict(str(tmp_path))
    with pytest.raises(ValueError, match="architecture record"):
        pred.predict_batch(params, doc, {"tokens": np.zeros((1, 2), np.int32)})


def test_config_from_meta_roundtrip():
    """from_meta inverts to_meta across the JSON boundary (tuples ride
    as lists) for every family that carries a config dataclass."""
    import json

    from edl_tpu.models import bert, llama, moe, resnet

    for cfg in (
        resnet.ResNetConfig.tiny(num_classes=5),
        bert.BertConfig.tiny(vocab=64),
        moe.MoEConfig.tiny(vocab=64),
        llama.LlamaConfig.tiny(vocab=64),
    ):
        meta = json.loads(json.dumps(cfg.to_meta()))
        back = type(cfg).from_meta(meta)
        for f in ("vocab", "d_model", "widths", "num_classes"):
            if hasattr(cfg, f):
                assert getattr(back, f) == getattr(cfg, f), f


def test_cli_predict_end_to_end(tmp_path):
    """The CLI verb over a real export + npz input, in a subprocess
    (the consumer's actual invocation)."""
    from edl_tpu.models import ctr

    export_dir = tmp_path / "export"
    rows = _ctr_export(export_dir)
    npz = tmp_path / "rows.npz"
    np.savez(npz, **rows)
    out_npz = tmp_path / "scored.npz"
    r = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli.main", "predict",
            str(export_dir), "--input", str(npz), "--out", str(out_npz),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr
    assert "family=ctr" in r.stdout and "auc=" in r.stdout
    with np.load(out_npz) as z:
        assert z["prob"].shape == (96,)
