"""End-to-end: submit YAML → controller creates workers → training runs →
autoscaler rescales → in-place reshard (no restart) → job succeeds.

The SURVEY §7 "minimum end-to-end slice", including the kill-one-worker
elasticity check and the stall metric flowing into job status.
"""

import jax
import numpy as np
import optax

from edl_tpu.api.job import JobPhase, TrainingJob
from edl_tpu.cluster.fake import FakeCluster, FakeHost
from edl_tpu.controller.controller import Controller
from edl_tpu.models import ctr, linreg
from edl_tpu.runtime.data import ElasticDataQueue, QueueBatcher
from edl_tpu.runtime.local import LocalJobRunner

JOB_YAML = """
apiVersion: edl-tpu.org/v1
kind: TrainingJob
metadata: {name: fit-a-line}
spec:
  fault_tolerant: true
  passes: 2
  worker:
    entrypoint: "python train_ft.py"
    min_replicas: 2
    max_replicas: 4
    resources:
      requests: {cpu: "500m", memory: "1Gi", tpu: 2}
      limits: {tpu: 2}
"""


def fleet(n=4):
    return FakeCluster(hosts=[FakeHost(f"h{i}", 8000, 16000, 2) for i in range(n)])


def test_submit_train_rescale_succeed(cpu_devices):
    cluster = fleet()
    ctl = Controller(cluster, max_load_desired=1.0)
    job = TrainingJob.from_yaml(JOB_YAML)
    cluster.submit_job(job)
    ctl.step()
    assert ctl.phase_of("fit-a-line") == JobPhase.RUNNING

    x, y = linreg.synthetic_dataset(2048)
    cursor = {"i": 0}

    def data_fn(bs):
        lo = cursor["i"] % (2048 - bs)
        cursor["i"] += bs
        return {"x": x[lo : lo + bs], "y": y[lo : lo + bs]}

    runner = LocalJobRunner(
        ctl,
        job,
        linreg.loss_fn,
        optax.sgd(0.05),
        linreg.init_params(jax.random.PRNGKey(0)),
        per_chip_batch=16,
    )
    assert runner.trainer.n_workers == 2

    runner.trainer.train_steps(data_fn, 5)
    # autoscaler grows the job into the idle fleet: 2 -> 4 workers
    ctl.autoscaler.tick()
    assert ctl.phase_of("fit-a-line") == JobPhase.SCALING
    report = runner.trainer.train_steps(data_fn, 5)
    assert runner.trainer.n_workers == 4
    assert len(report.reshards) == 1
    assert report.reshards[0].stall_s < 30.0
    # reshard completion flowed back into job status
    assert ctl.phase_of("fit-a-line") == JobPhase.RUNNING
    assert job.status.reshard_count == 1
    assert job.status.last_reshard_stall_s == report.reshards[0].stall_s

    report = runner.run(data_fn, n_steps=5)
    assert ctl.phase_of("fit-a-line") == JobPhase.SUCCEEDED
    assert report.losses[-1] < report.losses[0] * 0.5
    assert int(runner.trainer.state.step) == 15  # zero restarts


def test_kill_worker_job_finishes_anyway(cpu_devices):
    # SURVEY §7: "kill one worker → job finishes anyway" — the autoscaler
    # squeeze path: worker dies, fleet shrinks, trainer reshards down.
    cluster = fleet()
    ctl = Controller(cluster, max_load_desired=1.0)
    job = TrainingJob.from_yaml(JOB_YAML)
    cluster.submit_job(job)
    ctl.step()
    ctl.autoscaler.tick()  # grow to 4

    queue = ElasticDataQueue(n_samples=640, chunk_size=64, passes=1)
    x, y = linreg.synthetic_dataset(640)
    batcher = QueueBatcher(
        queue, lambda t: {"x": x[t.start : t.end], "y": y[t.start : t.end]}
    )

    def data_fn(bs):
        b = batcher.next_batch(bs)
        if b is None:  # queue drained: recycle data to keep shapes stable
            return {"x": x[:bs], "y": y[:bs]}
        if b["x"].shape[0] < bs:
            # short tail: pad by wraparound so its samples still train
            b = {k: np.resize(v, (bs,) + v.shape[1:]) for k, v in b.items()}
        return b

    runner = LocalJobRunner(
        ctl,
        job,
        linreg.loss_fn,
        optax.sgd(0.05),
        linreg.init_params(jax.random.PRNGKey(0)),
        per_chip_batch=8,  # global batch stays <= chunk_size at any scale
    )
    runner.trainer.train_steps(data_fn, 2)

    # a host dies: its worker pod fails, fleet loses 2 chips; the k8s-side
    # replacement pod pends (cluster full) while the runtime reshards down
    # to the live membership and keeps training.
    victim_pod = next(p for p in cluster.pods.values() if p.role == "worker")
    cluster.remove_host(victim_pod.host)
    queue.release_worker("w-dead")
    cluster.reconcile()
    assert cluster.job_pods(job) == (5, 3, 1)  # 3 live, 1 pending, 1 dead
    ctl.autoscaler.tick()  # reference semantics: unstable job not retargeted

    report = runner.run(data_fn, queue=queue)
    assert queue.done()
    assert ctl.phase_of("fit-a-line") == JobPhase.SUCCEEDED
    assert runner.trainer.n_workers == 3  # resharded down to live members
    assert len(report.reshards) >= 1  # 4 -> 3 in place, zero restarts
