"""Continuous-batching serving engine (edl_tpu/serving/).

The correctness contract: batched slot-table decode is TOKEN-IDENTICAL
to sequential ``llama.generate`` under greedy decoding, for any
membership history — including requests admitted while others are
mid-decode and evicted while others continue. Plus: admission control,
serving metrics through the collector plumbing, and the `edl serve`
CLI consumer.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.monitor.collector import Collector, ServingSource
from edl_tpu.obs import events as flight
from edl_tpu.runtime.export import export_params
from edl_tpu.serving.engine import ContinuousBatchingEngine
from edl_tpu.serving.metrics import ServingMetrics
from edl_tpu.serving.scheduler import (
    AdmissionError,
    InterleavePolicy,
    Request,
    RequestQueue,
)

CFG = llama.LlamaConfig.tiny()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG)


def _sequential(prompt, max_new, params=PARAMS, cfg=CFG):
    toks = llama.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


# -- engine correctness ------------------------------------------------------


@pytest.mark.parametrize("horizon", [1, 4, 16])
def test_horizon_greedy_token_identity(horizon):
    """The fused-horizon acceptance contract: H decode steps per
    dispatch (per-slot termination ON DEVICE) emit exactly sequential
    ``generate``'s tokens — at H=1 (the classic per-token iteration),
    H=4 and H=16, with budgets deliberately NOT divisible by H and
    requests joining mid-stream so admission lands on block
    boundaries while other slots are mid-block."""
    prompts = [list(range(2, 2 + n)) for n in (4, 7, 3, 9, 5, 6)]
    max_news = [6, 3, 13, 5, 7, 9]  # none divisible by 4 or 16
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=3, max_len=64, horizon=horizon
    )
    for i in range(3):
        eng.submit(f"r{i}", prompts[i], max_news[i])
    eng.step()  # first block in flight
    for i in range(3, 6):  # join while a block is mid-pipeline
        eng.submit(f"r{i}", prompts[i], max_news[i])
    res = eng.run()
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(prompts[i], max_news[i]), (
            f"r{i} at horizon {horizon}"
        )
        assert res[f"r{i}"].outcome == "done"


def test_horizon_eos_mid_block():
    """EOS hit in the MIDDLE of a fused block freezes the row on
    device: the EOS token is the last emitted (outcome "eos"), later
    lanes of the block emit nothing, and slot-mates decode through the
    same block unaffected."""
    prompt = [5, 6, 7, 8]
    full = _sequential(prompt, 8)
    eos = full[2]  # 3rd token of an 8-budget request: mid-block at H=8
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, max_len=64,
                                   horizon=8)
    eng.submit("stops", prompt, 8, eos_id=eos)
    eng.submit("runs", [9, 10, 11], 6)
    res = eng.run()
    assert res["stops"].tokens == full[:3]
    assert res["stops"].outcome == "eos"
    assert res["runs"].tokens == _sequential([9, 10, 11], 6)
    assert res["runs"].outcome == "done"


def test_horizon_dispatch_amortization():
    """The perf contract behind the fused loop: decode-heavy traffic
    at H=8 runs >= 4x fewer device dispatches per generated token than
    H=1 (the regression the exp_serving --dryrun CI lane also pins)."""
    prompts = [[2, 3, 4], [5, 6], [7, 8, 9, 10]]
    dpt = {}
    for h in (1, 8):
        eng = ContinuousBatchingEngine(
            PARAMS, CFG, max_slots=3, max_len=64, horizon=h
        )
        for i, p in enumerate(prompts):
            eng.submit(f"r{i}", p, 40 + i)  # deep budgets: decode-bound
        eng.run()
        snap = eng.metrics.snapshot()
        assert snap["tokens_out"] == sum(40 + i for i in range(3))
        dpt[h] = snap["dispatches_per_token"]
        assert snap["dispatches_prefill"] == 3
    assert dpt[1] / dpt[8] >= 4.0, dpt


def test_paged_engine_matches_contiguous_engine():
    """Cross-engine parity: the block-table paged cache (block_size>0)
    and the contiguous per-slot cache serve the SAME workload to
    byte-identical greedy tokens — mid-stream joins included. The
    paged engine's own coverage lives in tests/test_paged_kv.py;
    this pins the two engine modes against EACH OTHER."""
    prompts = [list(range(2, 2 + n)) for n in (4, 9, 3, 7)]
    max_news = [6, 5, 11, 8]
    results = {}
    for mode, kw in (
        ("contiguous", {}),
        ("paged", {"block_size": 8, "prefix_cache": True}),
    ):
        eng = ContinuousBatchingEngine(
            PARAMS, CFG, max_slots=2, max_len=64, horizon=4, **kw
        )
        eng.submit("r0", prompts[0], max_news[0])
        eng.submit("r1", prompts[1], max_news[1])
        eng.step()
        eng.submit("r2", prompts[2], max_news[2])
        eng.submit("r3", prompts[3], max_news[3])
        results[mode] = {
            rid: r.tokens for rid, r in eng.run().items()
        }
    assert results["paged"] == results["contiguous"]
    for i in range(4):
        assert results["paged"][f"r{i}"] == _sequential(
            prompts[i], max_news[i]
        )


def test_donated_cache_second_use_raises():
    """The stale-buffer invariant: every dispatch donates kc/vc (and
    the slot-state vectors), so pre-dispatch references are DEAD — a
    second use raises from jax, and the engine's own invariant saw the
    buffers consumed (in-place update, no per-step cache copy)."""
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, max_len=32,
                                   horizon=4)
    kc0, vc0 = eng._kc, eng._vc
    ptr0 = kc0.unsafe_buffer_pointer()
    eng.submit("a", [1, 2, 3], 6)
    eng.step()  # prefill + first block both dispatched
    assert eng._donates is True  # CPU/TPU backends donate
    assert kc0.is_deleted() and vc0.is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(kc0)
    # buffer identity: the live cache occupies the ORIGINAL buffer's
    # memory — the update chain is genuinely in place, no per-dispatch
    # cache allocation + copy
    assert eng._kc.unsafe_buffer_pointer() == ptr0
    # the live handles still serve: the engine never touches the dead
    # references, and the request completes token-identically
    res = eng.run()
    assert res["a"].tokens == _sequential([1, 2, 3], 6)


def test_program_cache_lru_keeps_hot_entry():
    """Satellite: the module-level program caches evict the OLDEST
    entry at the cap instead of clearing everything (which dropped the
    hot decode program mid-traffic)."""
    from edl_tpu.serving import engine as eng_mod

    # engine program cache: oldest evicted, touched entry survives
    saved = eng_mod._programs.copy()
    try:
        eng_mod._programs.clear()
        for i in range(eng_mod._PROGRAM_CAP):
            eng_mod._memo(("fake", i), lambda: i)
        eng_mod._memo(("fake", 0), lambda: "miss")  # touch: now MRU
        eng_mod._memo(("fresh",), lambda: "new")  # evicts ("fake", 1)
        assert ("fake", 0) in eng_mod._programs
        assert ("fake", 1) not in eng_mod._programs
        assert ("fresh",) in eng_mod._programs
        assert len(eng_mod._programs) == eng_mod._PROGRAM_CAP
    finally:
        eng_mod._programs.clear()
        eng_mod._programs.update(saved)

    # llama generate cache: same policy
    saved = llama._generate_programs.copy()
    try:
        llama._generate_programs.clear()
        for i in range(llama._GENERATE_PROGRAM_CAP):
            llama._generate_programs[("fake", i)] = i
        llama.generate(
            PARAMS, jnp.asarray([[1, 2]], jnp.int32), CFG, max_new=2
        )
        assert len(llama._generate_programs) == llama._GENERATE_PROGRAM_CAP
        assert ("fake", 0) not in llama._generate_programs  # oldest out
        assert ("fake", 1) in llama._generate_programs  # rest intact
        real = [k for k in llama._generate_programs if k[0] != "fake"]
        assert len(real) == 1
        # a hit moves the real program to MRU — it survives the next
        # eviction instead of being the oldest casualty of a clear
        llama._generate_programs.move_to_end(real[0], last=False)
        llama.generate(
            PARAMS, jnp.asarray([[1, 2]], jnp.int32), CFG, max_new=2
        )
        assert next(reversed(llama._generate_programs)) == real[0]
    finally:
        llama._generate_programs.clear()
        llama._generate_programs.update(saved)


def test_batched_greedy_token_identical_with_midstream_join_evict():
    """The acceptance contract: a mixed-length prompt set served
    through 3 slots — with half the requests submitted only after
    others are mid-decode (join) and short-budget requests finishing
    while long ones continue (evict) — produces exactly sequential
    ``generate``'s tokens for every request."""
    prompts = [list(range(2, 2 + n)) for n in (4, 7, 3, 9, 5, 6, 8, 4)]
    max_news = [6, 3, 8, 5, 7, 2, 4, 8]  # mixed: evictions interleave
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=3, max_len=64)
    for i in range(4):
        eng.submit(f"r{i}", prompts[i], max_news[i])
    for _ in range(3):  # one admission per step: r0..r2 in, r3 queued
        eng.step()
    assert eng.active_slots >= 2 and eng.queue.depth >= 1
    for i in range(4, 8):  # join mid-stream
        eng.submit(f"r{i}", prompts[i], max_news[i])
    res = eng.run()
    assert set(res) == {f"r{i}" for i in range(8)}
    for i in range(8):
        got = res[f"r{i}"].tokens
        assert got == _sequential(prompts[i], max_news[i]), f"r{i}"
        assert res[f"r{i}"].outcome == "done"


def test_engine_eos_eviction():
    """A request stops at its EOS token (included in the output,
    outcome "eos") while slot-mates keep decoding to budget."""
    prompt = [5, 6, 7, 8]
    full = _sequential(prompt, 8)
    eos = full[2]  # greedy emits this 3rd — decode must stop there
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, max_len=64)
    eng.submit("stops", prompt, 8, eos_id=eos)
    eng.submit("runs", [9, 10, 11], 6)
    res = eng.run()
    assert res["stops"].tokens == full[:3]
    assert res["stops"].outcome == "eos"
    assert res["runs"].tokens == _sequential([9, 10, 11], 6)
    assert res["runs"].outcome == "done"


def test_engine_single_token_budget_and_slot_reuse():
    """max_new=1 completes at prefill (never occupies a decode step)
    and its slot is immediately reusable; the cache row left by a
    previous occupant never leaks into the next request's tokens."""
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=1, max_len=64)
    for i, (n, mn) in enumerate([(9, 7), (3, 1), (6, 5)]):
        prompt = list(range(1, 1 + n))
        eng.submit(f"r{i}", prompt, mn)
    res = eng.run()
    assert res["r1"].tokens == _sequential(list(range(1, 4)), 1)
    for i, (n, mn) in enumerate([(9, 7), (3, 1), (6, 5)]):
        assert res[f"r{i}"].tokens == _sequential(list(range(1, 1 + n)), mn)


def test_engine_drain_half_close_pins_admission():
    """Graceful drain (the fleet's drain-before-evict primitive):
    after ``half_close()`` no queued request is admitted — not one
    token is generated for them — while in-flight requests run to
    their full budget token-identically; ``drain()`` then hands the
    queued residuals back intact (order and fields preserved), and
    ``reopen()`` restores admission."""
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, max_len=64)
    eng.submit("in0", [1, 2, 3, 4], 6)
    eng.submit("in1", [5, 6, 7], 5)
    eng.step()  # admits in0 (one prefill per step)
    eng.step()  # admits in1 — both in flight now
    # these land in the queue behind a full slot table
    eng.submit("q0", [8, 9, 10], 4)
    eng.submit("q1", [11, 12, 13, 14], 3)
    assert eng.queue.depth == 2
    residual = eng.drain()
    # in-flight finished exactly as without the drain
    assert eng.results["in0"].tokens == _sequential([1, 2, 3, 4], 6)
    assert eng.results["in1"].tokens == _sequential([5, 6, 7], 5)
    assert eng.results["in0"].outcome == "done"
    # queued requests: zero tokens generated, residuals intact
    assert [r.rid for r in residual] == ["q0", "q1"]
    assert residual[0].prompt == [8, 9, 10]
    assert residual[0].max_new == 4
    assert residual[1].prompt == [11, 12, 13, 14]
    assert "q0" not in eng.results and "q1" not in eng.results
    assert eng.queue.depth == 0 and eng.active_slots == 0
    assert eng.draining and not eng.has_work
    # a half-closed engine refuses no submits (admission control is
    # the queue's job) but never starts them
    eng.submit("late", [2, 3], 2)
    eng.step()
    assert eng.active_slots == 0 and eng.queue.depth == 1
    # reopen: the engine serves again, token-identically
    eng.reopen()
    res = eng.run()
    assert res["late"].tokens == _sequential([2, 3], 2)
    ev_kinds = [r["kind"] for r in flight.default_recorder().records()]
    assert "serve.halfclose" in ev_kinds and "serve.drained" in ev_kinds


def test_engine_int8_records_compose():
    """The engine serves the weight-only int8 records unchanged
    (`edl serve --int8`): batched greedy tokens == sequential generate
    through the same records."""
    qp = jax.jit(llama.quantize_params_int8)(PARAMS)
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]
    eng = ContinuousBatchingEngine(qp, CFG, max_slots=2, max_len=64)
    for i, p in enumerate(prompts):
        eng.submit(f"q{i}", p, 5)
    res = eng.run()
    for i, p in enumerate(prompts):
        assert res[f"q{i}"].tokens == _sequential(p, 5, params=qp)


def test_engine_sharded_params_compose(tmp_path, cpu_devices):
    """The engine serves a sharded export (`edl serve --mesh`): params
    loaded onto a tp×fsdp mesh decode token-identically."""
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime.export import load_export_sharded

    export_params(
        str(tmp_path), PARAMS, step=1, dtype="float32",
        model_meta=CFG.to_meta(),
    )
    plan = MeshPlan.parse("tp=2,fsdp=2,dp", 8)
    loaded, _ = load_export_sharded(
        str(tmp_path), plan.build(), llama.param_pspecs(CFG, plan)
    )
    eng = ContinuousBatchingEngine(loaded, CFG, max_slots=2, max_len=32)
    eng.submit("a", [1, 2, 3, 4], 5)
    eng.submit("b", [5, 6, 7], 4)
    res = eng.run()
    assert res["a"].tokens == _sequential([1, 2, 3, 4], 5)
    assert res["b"].tokens == _sequential([5, 6, 7], 4)


def test_engine_sampling_shape_and_determinism():
    """Temperature sampling: deterministic under a fixed seed, tokens
    in-vocab, EOS/budget still honored."""
    runs = []
    for _ in range(2):
        eng = ContinuousBatchingEngine(
            PARAMS, CFG, max_slots=2, max_len=64, temperature=0.9, seed=11
        )
        eng.submit("s0", [1, 2, 3], 6)
        eng.submit("s1", [4, 5, 6, 7], 4)
        res = eng.run()
        runs.append({k: v.tokens for k, v in res.items()})
    assert runs[0] == runs[1]
    assert len(runs[0]["s0"]) == 6 and len(runs[0]["s1"]) == 4
    assert all(0 <= t < CFG.vocab for ts in runs[0].values() for t in ts)


# -- scheduler / admission control ------------------------------------------


def test_queue_admission_reasons():
    q = RequestQueue(max_total_len=32, max_depth=2, max_prompt_len=8,
                     max_new_cap=10)
    q.submit(Request("ok", [1, 2, 3], 4))
    with pytest.raises(AdmissionError) as e:
        q.submit(Request("long", list(range(9)), 4))
    assert e.value.reason == "prompt_too_long"
    with pytest.raises(AdmissionError) as e:
        q.submit(Request("cap", [1], 11))
    assert e.value.reason == "budget"
    with pytest.raises(AdmissionError) as e:
        q.submit(Request("slot", [1, 2, 3, 4, 5], 28))  # 5+28 > 32
    assert e.value.reason == "budget"
    with pytest.raises(AdmissionError) as e:
        q.submit(Request("empty", [], 4))
    assert e.value.reason == "bad_request"
    q.submit(Request("fill", [1], 4))
    with pytest.raises(AdmissionError) as e:
        q.submit(Request("over", [1], 4))
    assert e.value.reason == "queue_full"
    assert q.depth == 2
    assert q.pop().rid == "ok"  # FIFO


def test_engine_submit_rejections_counted():
    """Engine-level admission: vocab bounds and duplicate ids reject
    with typed reasons, and the metrics counters see every rejection."""
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=1, max_len=16)
    eng.submit("a", [1, 2], 3)
    with pytest.raises(AdmissionError) as e:
        eng.submit("bad", [1, CFG.vocab + 5], 3)
    assert e.value.reason == "bad_request"
    with pytest.raises(AdmissionError) as e:
        eng.submit("huge", [1, 2, 3], 99)  # 3+99 > 16
    assert e.value.reason == "budget"
    eng.run()
    with pytest.raises(AdmissionError) as e:
        eng.submit("a", [1, 2], 3)  # id already completed
    assert e.value.reason == "bad_request"
    snap = eng.metrics.snapshot()
    assert snap["submitted"] == 4
    assert snap["admitted"] == 1
    assert snap["rejected"] == 3
    assert snap["rejected_bad_request"] == 2
    assert snap["rejected_budget"] == 1


def test_interleave_policy_budget():
    p = InterleavePolicy(prefills_per_step=2)
    assert p.budget(free_slots=3, queue_depth=5) == 2
    assert p.budget(free_slots=1, queue_depth=5) == 1
    assert p.budget(free_slots=3, queue_depth=0) == 0
    # at most one prefill per step by default (decode must not starve)
    assert InterleavePolicy().budget(4, 4) == 1


def test_interleave_policy_block_budget():
    """Admission lands on block boundaries under a fused horizon: one
    boundary admits what H per-step boundaries would have, still
    capped by free slots and queue depth."""
    p = InterleavePolicy()
    assert p.block_budget(free_slots=8, queue_depth=9, horizon=4) == 4
    assert p.block_budget(free_slots=2, queue_depth=9, horizon=4) == 2
    assert p.block_budget(free_slots=8, queue_depth=1, horizon=4) == 1
    assert p.block_budget(free_slots=8, queue_depth=0, horizon=4) == 0
    # H=1 degenerates to the per-step budget exactly
    assert p.block_budget(4, 4, 1) == p.budget(4, 4) == 1
    assert InterleavePolicy(prefills_per_step=2).block_budget(8, 9, 4) == 8


# -- timeout accounting (the ISSUE-5 double-count audit) ---------------------


def test_timeout_shed_counts_once_as_rejected_never_completed():
    """A queued request shed at pop counts exactly ONCE, as
    rejected:timeout — never through on_finish, so `completed` and the
    outcome counter stay untouched (the shed request was never
    admitted)."""
    from edl_tpu.obs.metrics import MetricsRegistry

    t = [0.0]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=1, max_len=64, clock=lambda: t[0],
        metrics=ServingMetrics(clock=lambda: t[0],
                               registry=MetricsRegistry()),
    )
    eng.submit("busy", [1, 2, 3], 6)  # occupies the only slot
    eng.submit("stale", [4, 5, 6], 4, deadline_s=5.0)  # waits in queue
    t[0] = 10.0  # deadline passes while queued
    res = eng.run()
    assert res["stale"].outcome == "timeout" and res["stale"].tokens == []
    assert res["busy"].outcome == "done"
    m = eng.metrics
    assert m.rejected == {"timeout": 1}
    # exactly once: completed counts ONLY the admitted request, and the
    # outcome counter has no timeout entry (no on_finish for the shed)
    assert m.completed == 1
    assert m.outcomes == {"done": 1}
    snap = m.snapshot()
    assert snap["rejected_timeout"] == 1
    assert "outcome_timeout" not in snap
    # the registry twin agrees: 2 submitted, 1 rejected, 1 completed
    assert m._m_requests.value(event="submitted") == 2
    assert m._m_requests.value(event="rejected") == 1
    assert m._m_requests.value(event="completed") == 1


def test_timeout_eviction_counts_once_as_completed_never_rejected():
    """An in-flight slot past its deadline counts exactly ONCE, as
    completed{outcome=timeout} — never as a rejection — and keeps the
    tokens drained so far."""
    from edl_tpu.obs.metrics import MetricsRegistry

    t = [0.0]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=64, clock=lambda: t[0],
        metrics=ServingMetrics(clock=lambda: t[0],
                               registry=MetricsRegistry()),
    )
    eng.submit("slow", [1, 2, 3], 40, deadline_s=5.0)
    eng.submit("ok", [4, 5, 6], 4)
    for _ in range(3):
        eng.step()
    t[0] = 10.0  # slow's deadline passes mid-flight
    res = eng.run()
    assert res["slow"].outcome == "timeout"
    assert 0 < len(res["slow"].tokens) < 40  # partial tokens kept
    assert res["ok"].outcome == "done"
    m = eng.metrics
    assert m.rejected == {}  # never rejected:timeout for the evicted path
    assert m.outcomes["timeout"] == 1 and m.completed == 2
    assert m._m_requests.value(event="rejected") == 0
    assert m._m_requests.value(event="completed") == 2


def test_timeout_evicted_slot_reuse_leaks_no_stale_tokens():
    """The audit's correctness half: a deadline eviction is host-only
    (the device row keeps decoding), so a block dispatched BEFORE the
    eviction still carries the old request's tokens in that lane. The
    engine must drain those blocks before reusing the slot — the new
    occupant's output stays token-identical to sequential generate."""
    t = [0.0]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=1, max_len=64, horizon=4,
        clock=lambda: t[0],
    )
    eng.submit("old", [1, 2, 3], 20, deadline_s=5.0)
    eng.step()  # old admitted; one horizon-4 block left in flight
    assert eng._inflight
    t[0] = 10.0  # old's deadline passes with the block undrained
    eng.submit("new", [4, 5, 6], 6)
    res = eng.run()
    assert res["old"].outcome == "timeout"
    assert res["new"].outcome == "done"
    assert res["new"].tokens == _sequential([4, 5, 6], 6)
    # accounting stayed exactly-once through the reuse
    m = eng.metrics
    assert m.outcomes == {"timeout": 1, "done": 1}
    assert m.completed == 2 and m.rejected == {}


# -- metrics + collector plumbing -------------------------------------------


def test_metrics_ttft_and_throughput_deterministic_clock():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    m = ServingMetrics(clock=clock)
    m.on_submit("a")
    t[0] = 1.0
    m.on_admit("a", prompt_len=4)
    m.on_token("a")  # first token at 1.0 -> TTFT 1.0
    t[0] = 3.0
    for _ in range(3):
        m.on_token("a")
    m.on_finish("a", "done")
    m.on_step(1, 4, 2)
    snap = m.snapshot()
    assert snap["ttft_avg_s"] == pytest.approx(1.0)
    assert snap["tokens_out"] == 4
    # busy window = first admit (1.0) .. last token (3.0) -> 2 tok/s
    assert snap["agg_tokens_per_s"] == pytest.approx(2.0)
    assert snap["queue_depth"] == 2
    assert snap["slot_occupancy"] == pytest.approx(0.25)
    st = m.request_stats("a")
    assert st["ttft_s"] == pytest.approx(1.0)
    assert st["outcome"] == "done"


def test_metrics_per_block_tokens_and_dispatches():
    """Per-block accounting: on_tokens(rid, n) lands n tokens with one
    clock read; dispatch counters feed dispatches_per_token; TTFT is
    stamped by the admission-time on_token, NOT the block drain."""
    t = [0.0]
    m = ServingMetrics(clock=lambda: t[0])
    m.on_submit("a")
    t[0] = 1.0
    m.on_admit("a", prompt_len=4)
    m.on_dispatch("prefill")
    m.on_token("a")  # first token with the prefill: TTFT = 1.0
    t[0] = 9.0
    m.on_dispatch("decode")
    m.on_tokens("a", 8)  # one horizon-8 block drained at t=9
    m.on_finish("a", "done")
    snap = m.snapshot()
    assert snap["ttft_avg_s"] == pytest.approx(1.0)  # not 9.0
    assert snap["tokens_out"] == 9
    assert snap["dispatches_decode"] == 1
    assert snap["dispatches_prefill"] == 1
    assert snap["dispatches_per_token"] == pytest.approx(2 / 9)


def test_serving_source_through_collector():
    """Serving load rides the SAME collector plumbing as training load:
    ServingSource samples a live engine's metrics into MonitorSample
    and the render shows the SERVING block."""
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=2, max_len=32)
    col = Collector(ServingSource(eng.metrics), interval_s=0.0)
    eng.submit("a", [1, 2, 3], 4)
    eng.submit("b", [4, 5, 6, 7], 3)
    eng.run()
    s = col.poll()
    assert s.serving["admitted"] == 2
    assert s.serving["tokens_out"] == 7
    assert 0.0 < s.serving["slot_occupancy"] <= 1.0
    text = s.render()
    assert "SERVING:" in text and "tokens=7" in text
    # training-fleet samples keep their legacy render untouched
    from edl_tpu.monitor.collector import MonitorSample

    assert "SERVING" not in MonitorSample(ts=0.0).render()


# -- CLI + soak harness ------------------------------------------------------


def _env():
    return {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
    }


def test_cli_serve_jsonl(tmp_path):
    """`edl serve` end to end: JSONL feed in, JSONL completions out
    (submit order), admission rejections typed, metrics on stderr —
    and every completion token-identical to sequential generate."""
    export_params(
        str(tmp_path), PARAMS, step=1, dtype="float32",
        model_meta=CFG.to_meta(),
    )
    feed = tmp_path / "reqs.jsonl"
    feed.write_text(
        json.dumps({"id": "a", "prompt": [1, 2, 3, 4], "max_new": 5}) + "\n"
        + json.dumps({"prompt": [7, 8, 9], "max_new": 4}) + "\n"
        + json.dumps({"id": "big", "prompt": [1], "max_new": 500}) + "\n"
    )
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "serve", str(tmp_path),
            "--requests", str(feed), "--max-slots", "2", "--max-len", "32",
            "--metrics-port", "0",
        ],
        capture_output=True, text=True, env=_env(),
    )
    assert out.returncode == 0, out.stderr
    recs = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert [r["id"] for r in recs] == ["a", "req-2", "big"]
    assert recs[0]["tokens"] == _sequential([1, 2, 3, 4], 5)
    assert recs[1]["tokens"] == _sequential([7, 8, 9], 4)
    assert recs[0]["outcome"] == "done" and recs[0]["ttft_s"] >= 0
    assert recs[2]["outcome"] == "rejected:budget"
    assert "SERVING:" in out.stderr and "rejected=1" in out.stderr
    # obs surface: --metrics-port announces the endpoint and the
    # histogram-backed percentiles render in the final SERVING block
    assert "# metrics endpoint http://127.0.0.1:" in out.stderr
    assert "latency: ttft p50/p95/p99=" in out.stderr


def test_cli_serve_stdin_and_flag_validation(tmp_path):
    export_params(
        str(tmp_path), PARAMS, step=1, dtype="float32",
        model_meta=CFG.to_meta(),
    )
    out = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "serve", str(tmp_path),
         "--max-new", "3"],
        input=json.dumps({"id": "x", "prompt": [2, 3]}) + "\n",
        capture_output=True, text=True, env=_env(),
    )
    assert out.returncode == 0, out.stderr
    (rec,) = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert rec["tokens"] == _sequential([2, 3], 3)

    # flag/feed mistakes fail BEFORE any export loads
    bad = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "serve",
         str(tmp_path / "nowhere"), "--requests", str(tmp_path / "missing")],
        capture_output=True, text=True, env=_env(),
    )
    assert bad.returncode == 1 and "bad request feed" in bad.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "serve", str(tmp_path),
         "--temperature", "-1"],
        input="", capture_output=True, text=True, env=_env(),
    )
    assert bad.returncode == 1 and "temperature" in bad.stderr
    both = subprocess.run(
        [sys.executable, "-m", "edl_tpu.cli", "serve", str(tmp_path),
         "--int8", "--mesh", "tp=2"],
        input='{"prompt": [1]}\n',
        capture_output=True, text=True, env=_env(),
    )
    assert both.returncode == 1 and "mutually exclusive" in both.stderr


def test_generate_rejects_top_flags_at_greedy():
    """Satellite (ADVICE r5): library callers get the CLI's signal —
    generate() raises when greedy decoding would silently ignore
    explicit top_k/top_p."""
    with pytest.raises(ValueError, match="temperature > 0"):
        llama.generate(
            PARAMS, jnp.asarray([[1, 2]], jnp.int32), CFG, max_new=2, top_k=5
        )
    with pytest.raises(ValueError, match="temperature > 0"):
        llama.generate(
            PARAMS, jnp.asarray([[1, 2]], jnp.int32), CFG, max_new=2,
            top_p=0.5,
        )


def test_crd_env_admits_list_form():
    """Satellite (ADVICE r5): the CRD spec.env schema admits BOTH forms
    the client parser accepts — the string mapping and the k8s
    container-style [{name, value}] list."""
    import pathlib

    import yaml

    crd_path = pathlib.Path(__file__).resolve().parent.parent / "deploy/crd.yaml"
    (crd,) = list(yaml.safe_load_all(crd_path.read_text()))
    (v1,) = [v for v in crd["spec"]["versions"] if v["name"] == "v1"]
    env = v1["schema"]["openAPIV3Schema"]["properties"]["spec"][
        "properties"]["env"]
    forms = env["anyOf"]
    types = {f["type"] for f in forms}
    assert types == {"object", "array"}
    (listform,) = [f for f in forms if f["type"] == "array"]
    assert listform["items"]["required"] == ["name"]
    assert set(listform["items"]["properties"]) == {"name", "value"}


@pytest.mark.slow
def test_exp_serving_soak_batched_beats_sequential():
    """The throughput acceptance: the soak harness's continuous engine
    strictly beats one-request-at-a-time serving on a >=8-request
    mixed-length workload (CPU dryrun)."""
    out = subprocess.run(
        [sys.executable, "scripts/exp_serving.py"],
        capture_output=True, text=True, env=_env(),
        cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    assert out.returncode == 0, out.stderr
    assert "continuous-batching speedup" in out.stdout
    speedup = float(
        out.stdout.split("continuous-batching speedup: ")[1].split("x")[0]
    )
    assert speedup > 1.0, out.stdout
