"""Distributed tracing (edl_tpu/obs/disttrace.py) — context
propagation through spans/events/KV, the NTP-midpoint clock sync, the
offset-corrected fleet trace merge (adversarial: injected ±5 s skew,
torn windows, exactly-one flow link per client/server pair), the
critical-path extraction, straggler telemetry, /trace paging, and the
`edl trace` CLI verb. jax-free throughout."""

import json

import pytest

from edl_tpu import obs
from edl_tpu.obs import disttrace as dt
from edl_tpu.obs import events as flight
from edl_tpu.obs import fleet
from edl_tpu.obs import metrics as om
from edl_tpu.runtime.coordinator import PyCoordinator
from edl_tpu.utils import tracing


@pytest.fixture
def fresh_obs():
    reg = om.reset_default_registry()
    rec = flight.reset_default_recorder()
    yield reg, rec
    om.reset_default_registry()
    flight.reset_default_recorder()


# ---------------------------------------------------------------------------
# ids + context stack


def test_derived_trace_ids_are_deterministic_and_distinct():
    a = dt.derived_trace_id("step", "job", 0, 7)
    assert a == dt.derived_trace_id("step", "job", 0, 7)
    assert a != dt.derived_trace_id("step", "job", 0, 8)
    assert a != dt.derived_trace_id("reshard", 7)
    assert dt.new_id() != dt.new_id()


def test_root_and_child_context_nesting():
    assert dt.current() is None
    with dt.root("rid", "r1") as ctx:
        assert ctx.trace_id == dt.derived_trace_id("rid", "r1")
        assert ctx.parent_id is None
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.parent_id == ctx.span_id
    assert dt.current() is None


def test_spans_carry_and_nest_trace_context():
    tr = tracing.Tracer()
    with dt.root("reshard", 3):
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        with tr.span("sibling"):
            pass
    outer, inner, sibling = (
        {s.name: s for s in tr.spans()}[n]
        for n in ("outer", "inner", "sibling")
    )
    o_t, o_s, o_p = dt.ids_of(outer.attrs)
    i_t, _i_s, i_p = dt.ids_of(inner.attrs)
    s_t, _s_s, s_p = dt.ids_of(sibling.attrs)
    assert o_t == i_t == s_t == dt.derived_trace_id("reshard", 3)
    assert i_p == o_s  # nested span parents to the enclosing one
    assert s_p == o_p  # siblings share the root parent, not each other
    # outside a root, spans stay id-free (zero noise when untraced)
    with tr.span("untraced"):
        pass
    assert dt.ids_of({s.name: s for s in tr.spans()}["untraced"].attrs) == (
        None, None, None,
    )


def test_events_stamp_active_context(fresh_obs):
    _reg, rec = fresh_obs
    with dt.root("rid", "r9"):
        with tracing.tracer().span("serving.prefill", rid="r9"):
            flight.emit("serve.prefill", rid="r9")
    ev = rec.events(kind="serve.prefill")[-1]
    tid, sid, _ = dt.ids_of(ev.corr)
    assert tid == dt.derived_trace_id("rid", "r9")
    assert sid is not None
    # span + event agree on the trace — /trace and /events?rid= key
    sp = [s for s in tracing.tracer().spans("serving.prefill")][-1]
    assert dt.ids_of(sp.attrs)[0] == tid


def test_inject_extract_roundtrip_and_kv_side_key():
    with dt.root("step", "j", 0, 1) as ctx:
        d = dt.inject({})
        assert dt.extract(d) == dt.current()
        kv = {}
        dt.publish_ctx(kv.__setitem__, "j/go/0", tag="1")
        got = dt.fetch_ctx(kv.get, "j/go/0", tag="1")
        assert got is not None and got.trace_id == ctx.trace_id
        # a stale tag (previous step's leftover) is rejected
        assert dt.fetch_ctx(kv.get, "j/go/0", tag="2") is None
    assert dt.extract({}) is None
    # a raising kv_get degrades to None, never to the caller
    def boom(_k):
        raise ConnectionError("gone")
    assert dt.fetch_ctx(boom, "j/go/0", tag="1") is None


# ---------------------------------------------------------------------------
# clock sync


def test_clock_sync_midpoint_recovers_injected_skew():
    t = {"now": 100.0}
    local = lambda: t["now"]  # noqa: E731

    def remote():
        # symmetric 10 ms legs; remote clock runs 5 s AHEAD
        t["now"] += 0.01
        ts = t["now"] + 5.0
        t["now"] += 0.01
        return ts

    est = dt.ClockSync(clock=local).sample(remote, n=4)
    assert est is not None and est.n == 4
    assert est.offset_s == pytest.approx(5.0, abs=1e-9)
    assert est.rtt_s == pytest.approx(0.02, abs=1e-9)


def test_clock_sync_prefers_minimum_rtt_sample():
    t = {"now": 0.0, "i": 0}
    # sample 1: 2 s asymmetric round trip (bad midpoint); sample 2:
    # tight 2 ms round trip (good) — the jitter filter must pick #2
    legs = [(2.0, 0.0), (0.001, 0.001)]

    def remote():
        a, b = legs[t["i"]]
        t["i"] += 1
        t["now"] += a
        ts = t["now"] + 5.0
        t["now"] += b
        return ts

    est = dt.ClockSync(clock=lambda: t["now"]).sample(remote, n=2)
    assert est.rtt_s == pytest.approx(0.002, abs=1e-9)
    assert est.offset_s == pytest.approx(5.0, abs=1e-3)


def test_clock_sync_unsupported_and_failing_remote():
    assert dt.ClockSync().sample(lambda: None, n=3) is None

    def broken():
        raise ConnectionError("no TIME op")

    assert dt.ClockSync().sample(broken, n=3) is None
    est = dt.ClockEstimate.from_json('{"offset_s": 1.5, "rtt_s": 0.01}')
    assert est.offset_s == 1.5
    assert dt.ClockEstimate.from_json("torn{") is None


def test_pycoordinator_time_supports_handshake():
    c = PyCoordinator()
    est = dt.ClockSync().sample(c.time, n=3)
    assert est is not None
    assert abs(est.offset_s) < 1.0  # same process, same clock


# ---------------------------------------------------------------------------
# span windows + fleet merge (adversarial)


def _window(name_times, skew=0.0, trace=None, extra_args=None):
    """Fabricate a worker's span window: [(name, t_wall, dur), ...]
    with ``skew`` seconds added to its clock."""
    spans = []
    for i, (name, t, dur) in enumerate(name_times):
        args = dict(extra_args or {})
        if trace:
            args = dt.inject(args, trace[i])
        spans.append(
            {"name": name, "seq": i + 1, "t_wall": t + skew,
             "dur_s": dur, "tid": 1, "args": args}
        )
    return json.dumps({"meta": {"pid": 1}, "spans": spans})


def test_span_window_roundtrip_and_torn_tolerance():
    tr = tracing.Tracer()
    with tr.span("a", x=1):
        pass
    doc = dt.load_span_window(dt.span_window_json(tr))
    assert [s["name"] for s in doc["spans"]] == ["a"]
    assert doc["spans"][0]["args"]["x"] == 1
    assert doc["spans"][0]["t_wall"] == pytest.approx(
        tr.t0_wall + tr.spans()[0].start_s
    )
    # torn JSON -> None; partial records -> skipped, not fatal
    assert dt.load_span_window('{"spans": [{"name": "a"') is None
    part = dt.load_span_window(
        '{"spans": [{"name": "ok", "t_wall": 1.0},'
        ' {"dur_s": 0.5}, "junk", {"name": "no_time"}]}'
    )
    assert [s["name"] for s in part["spans"]] == ["ok"]


def test_merge_restores_ordering_under_5s_skew():
    # true causality: w0's span ends BEFORE w1's starts (0.1 s later),
    # but w1's wall clock runs 5 s ahead — raw timestamps would put
    # w1 5 s late... and with NEGATIVE skew, before w0 even started.
    for skew in (+5.0, -5.0):
        w0 = _window([("go", 1000.0, 0.05)])
        w1 = _window([("recv", 1000.1, 0.05)], skew=skew)
        doc = dt.merge_fleet_trace(
            {"w0": w0, "w1": w1}, offsets={"w1": -skew}
        )
        xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert xs["recv"]["ts"] - xs["go"]["ts"] == pytest.approx(
            0.1 * 1e6, abs=1.0
        )
        # worker identity survives: one pid per process, named
        names = {
            e["pid"]: e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert sorted(names.values()) == ["w0", "w1"]
        assert xs["go"]["pid"] != xs["recv"]["pid"]


def test_merge_links_exactly_one_client_server_pair():
    client = dt.TraceContext("t" * 16, "c" * 16, None)
    server = dt.TraceContext("t" * 16, "s" * 16, "c" * 16)
    bystander = dt.TraceContext("t" * 16, "b" * 16, "missing-parent")
    w0 = _window([("coord.go", 10.0, 0.01)], trace=[client])
    w1 = _window(
        [("coord.go.recv", 10.02, 0.001), ("other", 10.5, 0.01)],
        trace=[server, bystander],
    )
    doc = dt.merge_fleet_trace({"w0": w0, "w1": w1})
    flows = [e for e in doc["traceEvents"] if e.get("ph") in ("s", "f")]
    assert doc["flow_links"] == 1
    assert len(flows) == 2  # one start + one finish, same id
    s, f = (
        next(e for e in flows if e["ph"] == "s"),
        next(e for e in flows if e["ph"] == "f"),
    )
    assert s["id"] == f["id"]
    xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert s["pid"] == xs["coord.go"]["pid"]
    assert f["pid"] == xs["coord.go.recv"]["pid"]


def test_merge_skips_undecodable_windows():
    doc = dt.merge_fleet_trace(
        {"ok": _window([("a", 1.0, 0.1)]), "bad": "not json {"}
    )
    assert doc["workers"] == ["ok"]
    assert doc["skipped_windows"] == 1


def test_intra_process_parent_child_gets_no_flow_arrow():
    parent = dt.TraceContext("t" * 16, "p" * 16, None)
    child = dt.TraceContext("t" * 16, "q" * 16, "p" * 16)
    w = _window(
        [("outer", 1.0, 0.2), ("inner", 1.05, 0.1)], trace=[parent, child]
    )
    doc = dt.merge_fleet_trace({"w0": w})
    assert doc["flow_links"] == 0


# ---------------------------------------------------------------------------
# critical path


def test_critical_path_follows_longest_linked_chain():
    root = dt.TraceContext("t" * 16, "r" * 16, None)
    a = dt.TraceContext("t" * 16, "a" * 16, "r" * 16)   # 0.1 s branch
    b = dt.TraceContext("t" * 16, "b" * 16, "r" * 16)   # 0.5 s branch
    b2 = dt.TraceContext("t" * 16, "e" * 16, "b" * 16)  # extends b
    w0 = _window([("root", 0.0, 0.05)], trace=[root])
    w1 = _window(
        [("short", 0.06, 0.1), ("long", 0.06, 0.5), ("tail", 0.6, 0.2)],
        trace=[a, b, b2],
    )
    doc = dt.merge_fleet_trace({"w0": w0, "w1": w1})
    hops = dt.critical_path(doc, trace_id="t" * 16)
    assert [h["name"] for h in hops] == ["root", "long", "tail"]
    assert hops[1]["gap_s"] == pytest.approx(0.01, abs=1e-6)
    assert hops[0]["worker"] == "w0" and hops[1]["worker"] == "w1"


def test_critical_path_rid_matches_block_rids_and_time_orders():
    w = _window(
        [
            ("serving.prefill", 1.0, 0.02),
            ("serving.dispatch", 1.05, 0.001),
            ("serving.drain", 1.10, 0.01),
            ("serving.prefill", 2.0, 0.02),  # another request
        ],
    )
    doc = json.loads(w)
    doc["spans"][0]["args"]["rid"] = "r1"
    doc["spans"][1]["args"]["rids"] = ["r1", "r2"]
    doc["spans"][2]["args"]["rids"] = ["r1"]
    doc["spans"][3]["args"]["rid"] = "r2"
    merged = dt.merge_fleet_trace({"w0": json.dumps(doc)})
    hops = dt.critical_path(merged, rid="r1")
    assert [h["name"] for h in hops] == [
        "serving.prefill", "serving.dispatch", "serving.drain",
    ]
    assert dt.critical_path(merged, rid="zzz") == []
    assert "3 hops" in dt.render_critical_path(hops)


def test_critical_path_selects_derived_reshard_root():
    tr = tracing.Tracer()
    with dt.root("reshard", 2):
        with tr.span("reshard", reshard_epoch=2):
            with tr.span("reshard.device_transfer"):
                pass
    doc = dt.merge_fleet_trace({"w0": dt.span_window_doc(tr)})
    hops = dt.critical_path(doc, reshard_epoch=2)
    assert [h["name"] for h in hops] == ["reshard", "reshard.device_transfer"]
    assert dt.critical_path(doc, reshard_epoch=3) == []


# ---------------------------------------------------------------------------
# straggler primitives + fleet pass


def test_step_skew_and_barrier_waits_math():
    skew, slow, median = dt.step_skew({"w0": 0.1, "w1": 0.1, "w2": 0.3})
    assert slow == "w2"
    assert skew == pytest.approx(3.0)
    assert median == pytest.approx(0.1)
    assert dt.step_skew({"w0": 0.1}) == (0.0, None, 0.0)
    waits = dt.barrier_waits({"w0": 10.0, "w1": 10.4, "w2": 9.8})
    assert waits["w1"] == pytest.approx(0.0)  # last arriver waits 0
    assert waits["w2"] == pytest.approx(0.6)


def test_barrier_waits_from_fleet_events_latest_epoch():
    evs = [
        {"kind": "worker.join", "t_wall": 1.0,
         "corr": {"worker": "w0"}, "attrs": {"epoch": 1}},
        {"kind": "worker.join", "t_wall": 1.3,
         "corr": {"worker": "w1"}, "attrs": {"epoch": 1}},
        {"kind": "worker.join", "t_wall": 5.0,
         "corr": {"worker": "w0"}, "attrs": {"epoch": 2}},
        {"kind": "worker.join", "t_wall": 5.9,
         "corr": {"worker": "w1"}, "attrs": {"epoch": 2}},
        {"kind": "worker.hb", "t_wall": 9.0,
         "corr": {"worker": "w0"}, "attrs": {}},
    ]
    waits = dt.barrier_waits_from_events(evs)
    assert waits == {"w0": pytest.approx(0.9), "w1": pytest.approx(0.0)}
    assert dt.barrier_waits_from_events([]) == {}


def _push_worker_state(c, job, worker, step_s, n=40, join_t=None,
                       clock_off=None):
    reg = om.MetricsRegistry()
    h = reg.histogram("edl_train_step_seconds", "steps")
    for _ in range(n):
        h.observe(step_s)
    c.kv_put(fleet.metrics_key(job, worker), reg.snapshot_json())
    if join_t is not None:
        rec = flight.FlightRecorder(clock=lambda: join_t)
        rec.emit("worker.join", worker=worker, epoch=1)
        c.kv_put(fleet.events_key(job, worker), rec.window_json())
    if clock_off is not None:
        c.kv_put(
            fleet.clock_key(job, worker),
            dt.ClockEstimate(clock_off, 0.001, 3).to_json(),
        )


def test_collect_fleet_straggler_gauges_and_event(fresh_obs):
    _reg, rec = fresh_obs
    fleet._last_straggler = None  # reset the emit dedup
    c = PyCoordinator()
    c.register("w0", 1)
    c.register("w1", 1)
    _push_worker_state(c, "j", "w0", 0.01, join_t=100.0)
    _push_worker_state(c, "j", "w1", 0.10, join_t=102.5)
    merged = fleet.collect_fleet(c, "j")
    skew = merged.get("edl_step_skew_ratio").value()
    assert skew > 1.5
    waits = dict(
        (k[0], v[0]) for k, v in
        merged.get("edl_barrier_wait_seconds").samples()
    )
    assert waits["w0"] == pytest.approx(2.5)
    assert waits["w1"] == pytest.approx(0.0)
    det = rec.events(kind="straggler.detected")
    assert len(det) == 1 and det[0].corr["worker"] == "w1"
    # a second scrape with the same skew does not re-emit
    fleet.collect_fleet(c, "j")
    assert len(rec.events(kind="straggler.detected")) == 1


def test_fleet_events_apply_clock_offsets(fresh_obs):
    c = PyCoordinator()
    c.register("w0", 1)
    c.register("w1", 1)
    # w1's clock runs 5 s ahead; its event at TRUE time 100.2 reads
    # 105.2 — without correction it sorts after everything
    _push_worker_state(c, "j", "w0", 0.01, join_t=100.4)
    _push_worker_state(c, "j", "w1", 0.01, join_t=105.2, clock_off=-5.0)
    evs = [e for e in fleet.collect_fleet_events(c, "j")
           if e["kind"] == "worker.join"]
    assert [e["corr"]["worker"] for e in evs] == ["w1", "w0"]
    assert evs[0]["t_wall"] == pytest.approx(100.2)
    raw = [e for e in fleet.collect_fleet_events(c, "j", apply_clock=False)
           if e["kind"] == "worker.join"]
    assert [e["corr"]["worker"] for e in raw] == ["w0", "w1"]


def test_collect_fleet_trace_end_to_end(fresh_obs):
    c = PyCoordinator()
    c.register("w0", 1)
    c.kv_put(fleet.trace_key("j", "w0"), _window([("train.step", 50.0, 0.2)]))
    c.kv_put(
        fleet.clock_key("j", "w0"),
        dt.ClockEstimate(2.0, 0.001, 3).to_json(),
    )
    doc = fleet.collect_fleet_trace(c, "j")
    assert set(doc["workers"]) == {"coordinator", "w0"}
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert any(e["args"].get("worker") == "w0" for e in xs)


# ---------------------------------------------------------------------------
# pusher + exporter surfaces


def test_pusher_publishes_trace_window_and_refreshes_clock(fresh_obs):
    tr = tracing.Tracer()
    with tr.span("a"):
        pass
    got = {}
    ticks = {"clock": 0}

    def clock_refresh():
        ticks["clock"] += 1

    p = obs.MetricsPusher(
        lambda payload: got.__setitem__("m", payload),
        interval_s=10.0,
        trace_publish=lambda payload: got.__setitem__("t", payload),
        tracer=tr,
        clock_refresh=clock_refresh,
    )
    assert p.push_once()
    doc = dt.load_span_window(got["t"])
    assert [s["name"] for s in doc["spans"]] == ["a"]
    assert ticks["clock"] == 1
    assert "\n" not in got["t"]  # KV line protocol


def test_exporter_trace_paging(fresh_obs):
    tr = tracing.Tracer()
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    exp = obs.MetricsExporter(om.MetricsRegistry(), tracer=tr).start()
    try:
        full = json.loads(obs.scrape(exp.url, "/trace"))
        xs = [e for e in full["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 5
        meta = next(
            e for e in full["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "edl_tracer"
        )
        assert meta["args"]["max_seq"] == 5
        page = json.loads(obs.scrape(exp.url, "/trace?since=3"))
        names = [e["name"] for e in page["traceEvents"] if e.get("ph") == "X"]
        assert names == ["s3", "s4"]  # seq 4, 5
        capped = json.loads(obs.scrape(exp.url, "/trace?n=2"))
        names = [e["name"] for e in capped["traceEvents"] if e.get("ph") == "X"]
        assert names == ["s3", "s4"]
        empty = json.loads(obs.scrape(exp.url, "/trace?since=5"))
        assert not [e for e in empty["traceEvents"] if e.get("ph") == "X"]
        # the cursor survives an empty page (puller can resume)
        meta = next(
            e for e in empty["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "edl_tracer"
        )
        assert meta["args"]["max_seq"] == 5
    finally:
        exp.stop()


def test_exporter_fleet_trace_source(fresh_obs):
    doc = {"traceEvents": [], "workers": ["w0"], "flow_links": 0}
    exp = obs.MetricsExporter(
        om.MetricsRegistry(), trace_source=lambda: doc
    ).start()
    try:
        got = json.loads(obs.scrape(exp.url, "/trace"))
        assert got["workers"] == ["w0"]
    finally:
        exp.stop()


# ---------------------------------------------------------------------------
# `edl trace` CLI


def test_cli_trace_critical_path_and_assert(tmp_path, capsys):
    from edl_tpu.cli.main import main as cli_main

    tr = tracing.Tracer()
    with dt.root("reshard", 0):
        with tr.span("reshard", reshard_epoch=0):
            with tr.span("reshard.build_mesh"):
                pass
    doc = dt.merge_fleet_trace({"w0": dt.span_window_doc(tr)})
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(doc))
    assert cli_main(
        ["trace", str(p), "--reshard-epoch", "0", "--assert-critical-path"]
    ) == 0
    out = capsys.readouterr().out
    assert "critical path" in out and "reshard.build_mesh" in out
    assert cli_main(
        ["trace", str(p), "--rid", "absent", "--assert-critical-path"]
    ) == 1
    capsys.readouterr()  # drain
    assert cli_main(["trace", str(p), "--reshard-epoch", "0", "--json"]) == 0
    out = capsys.readouterr().out
    payload = json.loads(out.splitlines()[-1])
    assert [h["name"] for h in payload["hops"]] == [
        "reshard", "reshard.build_mesh",
    ]
    assert cli_main(["trace", str(tmp_path / "missing.json")]) == 2
