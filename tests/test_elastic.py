"""ElasticTrainer: in-place reshard with zero restarts.

The north-star behavior (BASELINE.md): scale 2→4→1 workers mid-training
with state carried bit-exactly through each reshard, stall timed, and
the loss curve continuing as if nothing happened.
"""

import jax
import numpy as np
import optax

from edl_tpu.api.job import MeshSpec
from edl_tpu.models import ctr, linreg
from edl_tpu.parallel import sharding as shd
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.train.trainer import TrainState


def linreg_data_fn():
    x, y = linreg.synthetic_dataset(4096)
    state = {"i": 0}

    def fn(batch_size):
        lo = state["i"] % (4096 - batch_size)
        state["i"] += batch_size
        return {"x": x[lo : lo + batch_size], "y": y[lo : lo + batch_size]}

    return fn


def test_elastic_rescale_preserves_training(cpu_devices):
    tr = ElasticTrainer(
        linreg.loss_fn,
        optax.sgd(0.05),
        chips_per_worker=2,
        per_chip_batch=16,
    )
    tr.start(linreg.init_params(jax.random.PRNGKey(0)), n_workers=2)
    assert tr.n_devices == 4
    data = linreg_data_fn()
    tr.train_steps(data, 10)
    params_before = shd.to_host(tr.state.params)

    tr.request_rescale(4)  # grow 2 -> 4 workers (8 devices)
    tr.train_steps(data, 1)
    assert tr.n_devices == 8
    assert tr.global_batch_size == 128
    # exactly one reshard, params carried over bit-exactly at the boundary
    assert len(tr.report.reshards) == 1
    ev = tr.report.reshards[0]
    assert (ev.from_workers, ev.to_workers) == (2, 4)
    assert ev.stall_s < 30.0  # the north-star bound
    assert ev.step == 10

    tr.train_steps(data, 9)
    tr.request_rescale(1)  # shrink 4 -> 1 (failure/squeeze)
    tr.train_steps(data, 10)
    assert tr.n_devices == 2
    assert len(tr.report.reshards) == 2
    # training made progress across all three mesh incarnations
    losses = tr.report.losses
    assert losses[-1] < losses[0] * 0.5
    assert tr.report.steps == 30
    assert int(tr.state.step) == 30  # no restart: step count never reset


def test_reshard_is_bitexact(cpu_devices):
    # Snapshot -> remesh -> restore must not change a single bit of state.
    tr = ElasticTrainer(linreg.loss_fn, optax.adam(1e-2), chips_per_worker=1)
    tr.start(linreg.init_params(jax.random.PRNGKey(1)), n_workers=4)
    data = linreg_data_fn()
    tr.train_steps(data, 5)
    before = ckpt.snapshot(tr.state)
    tr.request_rescale(8)
    tr._maybe_rescale()
    after = ckpt.snapshot(tr.state)
    jax.tree_util.tree_map(np.testing.assert_array_equal, before.params, after.params)
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, before.opt_state, after.opt_state
    )


def test_elastic_fsdp_ctr(cpu_devices):
    # CTR with an fsdp axis: reshard re-slices the embedding across the
    # new mesh (the Llama-elastic-FSDP mechanism, at CTR scale).
    tr = ElasticTrainer(
        ctr.loss_fn,
        optax.adam(1e-2),
        mesh_spec=MeshSpec(fsdp=2),
        chips_per_worker=2,
        per_chip_batch=32,
    )
    tr.start(ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), n_workers=2)
    rng = np.random.RandomState(0)

    def data(bs):
        return ctr.synthetic_batch(rng, bs, vocab=4096)

    tr.train_steps(data, 5)
    emb = tr.state.params["embedding"]
    assert {s.data.shape for s in emb.addressable_shards} == {(2048, 8)}
    tr.request_rescale(4)
    tr.train_steps(data, 5)
    emb = tr.state.params["embedding"]
    # fsdp stays 2, dp grew: vocab still sharded 2-way over fsdp
    assert tr.plan.describe() == {"dp": 4, "fsdp": 2}
    assert {s.data.shape for s in emb.addressable_shards} == {(2048, 8)}
    assert tr.report.reshards[0].stall_s < 30.0


def test_checkpoint_roundtrip(tmp_path, cpu_devices):
    params = linreg.init_params(jax.random.PRNGKey(0))
    tx = optax.adam(1e-2)
    state = TrainState.create(params, tx)
    host = ckpt.snapshot(state)
    ckpt.save(str(tmp_path / "c1"), host, {"job": "demo"})
    like = TrainState.create(linreg.init_params(jax.random.PRNGKey(42)), tx)
    loaded = ckpt.load(str(tmp_path / "c1"), like)
    jax.tree_util.tree_map(np.testing.assert_array_equal, host.params, loaded.params)
    assert ckpt.load_metadata(str(tmp_path / "c1")) == {"job": "demo"}


def test_staged_reshard_preserves_state_across_mesh_change(cpu_devices):
    """staged_reshard (overlapped host pipeline) must be value-identical
    to snapshot+restore when moving state onto a different-size mesh."""
    import numpy as np
    import optax

    from edl_tpu.models import ctr
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime import checkpoint as ckpt
    from edl_tpu.train.trainer import TrainState, shard_state

    import jax

    from edl_tpu.parallel import sharding as shd

    plan8 = MeshPlan.data_parallel(8)
    mesh8 = plan8.build()
    tx = optax.adam(1e-3)
    chunk = shd._CHUNK_BYTES
    try:
        shd._CHUNK_BYTES = 1 << 12  # 4 KB: exercise multi-piece path
        state = shard_state(
            TrainState.create(
                ctr.init_params(jax.random.PRNGKey(0), vocab=2048, emb=8), tx
            ),
            plan8,
            mesh8,
        )
        plan4 = MeshPlan.data_parallel(4)
        mesh4 = plan4.build(jax.devices()[:4])
        out = ckpt.staged_reshard(state, plan4, mesh4, stage="f32")  # pin: exactness test
        ref = ckpt.restore(ckpt.snapshot(state), plan4, mesh4)
        for a, b in zip(
            jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(ref)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(out.step) == int(state.step)
    finally:
        shd._CHUNK_BYTES = chunk


def test_staged_reshard_onto_fsdp_mesh(cpu_devices):
    """Regression: pieces uploaded to an fsdp-sharded destination must
    split on the target's dim-0 partition count — ragged pieces make
    device_put raise (vocab 2048 / 8-way fsdp; tiny piece size forces
    many pieces whose raw ceil-rows would not divide by 8)."""
    import numpy as np
    import optax

    from edl_tpu.models import ctr
    from edl_tpu.parallel import sharding as shd
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime import checkpoint as ckpt
    from edl_tpu.train.trainer import TrainState, shard_state

    import jax

    src_plan = MeshPlan.data_parallel(1)  # single-device: pieces split
    src_mesh = src_plan.build(jax.devices()[:1])
    fsdp_plan = MeshPlan.fsdp_only(8)
    fsdp_mesh = fsdp_plan.build()
    tx = optax.adam(1e-3)
    chunk = shd._CHUNK_BYTES
    try:
        shd._CHUNK_BYTES = 3 << 10  # odd size: ceil-rows not % 8
        state = shard_state(
            TrainState.create(
                ctr.init_params(jax.random.PRNGKey(0), vocab=2048, emb=8), tx
            ),
            src_plan,
            src_mesh,
        )
        out = ckpt.staged_reshard(state, fsdp_plan, fsdp_mesh, stage="f32")  # pin: exactness test
        ref = ckpt.restore(ckpt.snapshot(state), fsdp_plan, fsdp_mesh)
        for a, b in zip(
            jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(ref)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        shd._CHUNK_BYTES = chunk


def test_reshard_event_flags_host_fallback(cpu_devices, monkeypatch):
    """When the direct device move fails, the reshard completes through
    host staging and the event is instrumented as a fallback (VERDICT
    r1 #7: measure when the slow path triggers)."""
    from edl_tpu.runtime import elastic as el

    def _boom(*a, **k):
        raise RuntimeError("transfer layer down")

    monkeypatch.setattr(el, "_device_reshard", _boom)
    tr = ElasticTrainer(
        linreg.loss_fn,
        optax.sgd(1e-2),
        mesh_spec=MeshSpec(),
        per_chip_batch=16,
    )
    tr.start(linreg.init_params(jax.random.PRNGKey(0)), 2)
    data = linreg_data_fn()
    tr.train_steps(data, 2)
    tr.request_rescale(4)
    rep = tr.train_steps(data, 2)
    assert [e.fallback for e in rep.reshards] == [True]
    assert tr.n_workers == 4
    # and the fast path reports fallback=False
    monkeypatch.undo()
    tr.request_rescale(2)
    rep = tr.train_steps(data, 2)
    assert rep.reshards[-1].fallback is False


def test_host_fallback_stall_model():
    # 17 GB on one host at 1 GiB/s: 17 s — inside the 30 s budget
    s = ckpt.host_fallback_stall_model(17 * (1 << 30), 1, 1 << 30)
    assert abs(s - 17.0) < 1e-9
    # spreading over 8 hosts divides the per-host bytes
    assert ckpt.host_fallback_stall_model(17 * (1 << 30), 8, 1 << 30) == s / 8
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ckpt.host_fallback_stall_model(1, 0, 1.0)


def test_staged_reshard_int8_moment_staging(cpu_devices):
    """int8 moment staging (VERDICT r2 #4): params move EXACTLY, Adam
    moments within 1/127 of their block absmax, and wire bytes for the
    moments drop ~4x (ops/quant.py; stall measured on hardware by
    bench.py)."""
    import numpy as np
    import optax

    import jax

    from edl_tpu.models import ctr
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime import checkpoint as ckpt
    from edl_tpu.train.trainer import (
        TrainState,
        global_batch,
        make_train_step,
        shard_state,
    )

    plan = MeshPlan.data_parallel(4)
    mesh = plan.build(jax.devices()[:4])
    tx = optax.adam(1e-3)
    state = shard_state(
        TrainState.create(
            ctr.init_params(jax.random.PRNGKey(0), vocab=4096, emb=8), tx
        ),
        plan,
        mesh,
    )
    # one real step so moments are non-trivial
    step = make_train_step(ctr.make_loss_fn(), tx, plan, mesh, donate=False)
    b = ctr.synthetic_batch(np.random.RandomState(0), 64, vocab=4096)
    state, _ = step(state, global_batch(b, plan, mesh))

    plan2 = MeshPlan.create(dp=2, fsdp=4)
    mesh2 = plan2.build()
    out = ckpt.staged_reshard(state, plan2, mesh2, stage="int8")
    for a, bb in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(out.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    mu0 = np.asarray(state.opt_state[0].mu["embedding"])
    mu1 = np.asarray(out.opt_state[0].mu["embedding"])
    denom = np.maximum(np.abs(mu0).max(axis=-1, keepdims=True), 1e-12)
    assert (np.abs(mu0 - mu1) / denom).max() <= 1 / 127 + 1e-6


def test_stall_model_staging_aware():
    """The 8B stall model charges compressed moments honestly: an
    Adam-shaped state halves, an adafactor-shaped state barely moves."""
    from edl_tpu.runtime import checkpoint as ckpt

    gb = 1 << 30
    bw = 1 * gb
    adam = ckpt.host_fallback_stall_model(
        30 * gb, 1, bw, moment_bytes=20 * gb, stage="int8"
    )
    assert abs(adam - (10 + 20 * 0.26)) < 1e-6
    adafactor = ckpt.host_fallback_stall_model(
        17 * gb, 1, bw, moment_bytes=1 * gb, stage="int8"
    )
    assert 16.2 < adafactor < 16.3
    raw = ckpt.host_fallback_stall_model(30 * gb, 1, bw)
    assert raw == 30.0
