"""BERT and ResNet workloads: shapes, learnability, sharded training.

These are the "ResNet/BERT-class elastic DP" workloads of SURVEY §7.8;
each must train under the sharded train step with its partition specs
on a multi-axis mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import bert, resnet
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import (
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def _train(loss_fn, params, pspecs, plan, data_fn, steps, devices, lr=1e-2):
    mesh = plan.build(devices[: plan.size()])
    tx = optax.adam(lr)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    step = make_train_step(loss_fn, tx, plan, mesh, pspecs)
    losses = []
    for i in range(steps):
        state, m = step(state, global_batch(data_fn(i), plan, mesh))
        losses.append(float(m["loss"]))
    return state, losses


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------


def test_bert_forward_shapes(cpu_devices):
    cfg = bert.BertConfig.tiny(vocab=64)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = bert.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32


def test_bert_mlm_learns(cpu_devices):
    cfg = bert.BertConfig.tiny(vocab=32)
    rng = np.random.RandomState(0)
    plan = MeshPlan.data_parallel(4)

    def data_fn(i):
        return bert.synthetic_mlm_batch(rng, 8, 16, cfg.vocab)

    _, losses = _train(
        bert.make_loss_fn(cfg),
        bert.init_params(jax.random.PRNGKey(0), cfg),
        bert.param_pspecs(cfg, plan),
        plan,
        data_fn,
        steps=30,
        devices=cpu_devices,
    )
    assert losses[-1] < losses[0] * 0.7  # masked repeats are predictable


def test_bert_fsdp_tp_sharded_step(cpu_devices):
    cfg = bert.BertConfig.tiny(vocab=64)
    plan = MeshPlan.create(dp=2, fsdp=2, tp=2)
    rng = np.random.RandomState(1)

    def data_fn(i):
        return bert.synthetic_mlm_batch(rng, 8, 16, cfg.vocab)

    state, losses = _train(
        bert.make_loss_fn(cfg),
        bert.init_params(jax.random.PRNGKey(1), cfg),
        bert.param_pspecs(cfg, plan),
        plan,
        data_fn,
        steps=2,
        devices=cpu_devices,
    )
    assert int(state.step) == 2
    assert np.isfinite(losses).all()


# ---------------------------------------------------------------------------
# ResNet
# ---------------------------------------------------------------------------


def test_resnet_forward_shapes(cpu_devices):
    cfg = resnet.ResNetConfig.tiny(num_classes=10)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    images = jnp.zeros((2, 32, 32, 3))
    logits = resnet.forward(params, images, cfg)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet_learns_dp(cpu_devices):
    cfg = resnet.ResNetConfig.tiny(num_classes=4)
    rng = np.random.RandomState(0)
    plan = MeshPlan.data_parallel(4)

    def data_fn(i):
        return resnet.synthetic_batch(rng, 8, size=16, num_classes=4)

    _, losses = _train(
        resnet.make_loss_fn(cfg),
        resnet.init_params(jax.random.PRNGKey(0), cfg),
        resnet.param_pspecs(cfg, plan),
        plan,
        data_fn,
        steps=25,
        devices=cpu_devices,
        lr=3e-3,
    )
    assert losses[-1] < losses[0] * 0.8


def test_resnet_fsdp_sharded_step(cpu_devices):
    cfg = resnet.ResNetConfig.tiny(num_classes=10)
    plan = MeshPlan.create(dp=2, fsdp=2)
    rng = np.random.RandomState(2)

    def data_fn(i):
        return resnet.synthetic_batch(rng, 8, size=16)

    state, losses = _train(
        resnet.make_loss_fn(cfg),
        resnet.init_params(jax.random.PRNGKey(2), cfg),
        resnet.param_pspecs(cfg, plan),
        plan,
        data_fn,
        steps=2,
        devices=cpu_devices,
    )
    assert int(state.step) == 2
    assert np.isfinite(losses).all()


def test_every_workload_defines_a_working_eval():
    """Every model family's worker workload carries a held-out eval
    hook (EDL_EVAL_DIR contract: linreg RMSE, ctr AUC, llama/moe
    perplexity, bert masked accuracy, resnet top-1) that produces a
    finite metric on its own batch format."""
    import numpy as np

    from edl_tpu.runtime.worker_main import WORKLOADS, WorkerConfig

    for name, make in WORKLOADS.items():
        cfg = WorkerConfig(
            job="t", worker_id="w", coord_host="", coord_port=0,
            min_workers=1, max_workers=1, fault_tolerant=False,
            model=name, vocab=256, seq_len=16,
        )
        wl = make(cfg)
        assert wl.eval_fn is not None, f"{name} has no eval_fn"
        params = wl.init_params()
        rows = wl.batch_fn(0, 32)
        metric = wl.eval_fn(params, rows)
        assert np.isfinite(metric), (name, metric)
