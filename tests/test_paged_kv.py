"""Paged KV cache (block pool + per-slot block tables).

The correctness contract: a paged engine (``block_size > 0``) emits
GREEDY tokens identical to sequential ``llama.generate`` — and hence
to the contiguous engine — at every horizon, for any membership
history: joins mid-stream, prompts straddling block boundaries,
mid-block EOS, prefix-cache hits (shared blocks + copy-on-write),
chunked prefill, pool-pressure preemption, and across fault-injected
crash/recovery that rebuilds pool and tables from host truth.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.serving import paged
from edl_tpu.serving.engine import ContinuousBatchingEngine
from edl_tpu.utils import faults

CFG = llama.LlamaConfig.tiny()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _sequential(prompt, max_new):
    toks = llama.generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CFG, max_new=max_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _paged_engine(**kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 8)
    return ContinuousBatchingEngine(PARAMS, CFG, **kw)


# -- host-side allocator / prefix cache units --------------------------------


def test_block_allocator_basics():
    a = paged.BlockAllocator(5, 8)
    assert a.free_blocks == 4  # block 0 is scratch
    b1, b2 = a.alloc(), a.alloc()
    assert b1 == 1 and b2 == 2  # ascending, never scratch
    assert a.allocated_blocks == 2
    a.incref(b1)
    assert a.refcount(b1) == 2
    assert a.free(b1) is False  # one ref remains
    assert a.free(b1) is True  # back to the pool
    with pytest.raises(ValueError):
        a.free(b1)  # double free
    with pytest.raises(ValueError):
        a.incref(paged.SCRATCH)
    assert a.free(paged.SCRATCH) is False  # scratch no-op
    while a.alloc() is not None:
        pass
    assert a.free_blocks == 0  # exhaustion returns None, never raises


def test_chain_keys_and_blocks_for():
    toks = list(range(20))
    keys = paged.chain_keys(toks, 8)
    assert keys == [tuple(range(8)), tuple(range(16))]  # full blocks only
    assert paged.blocks_for(0, 8) == 0
    assert paged.blocks_for(1, 8) == 1
    assert paged.blocks_for(8, 8) == 1
    assert paged.blocks_for(9, 8) == 2


def test_prefix_cache_match_insert_evict():
    a = paged.BlockAllocator(8, 4)
    c = paged.PrefixCache(a)
    b1, b2 = a.alloc(), a.alloc()
    k = paged.chain_keys(list(range(8)), 4)
    c.insert(k[0], b1)
    c.insert(k[1], b2)
    assert a.refcount(b1) == 2  # cache holds its own ref
    assert c.match(list(range(8))) == [b1, b2]
    assert c.match(list(range(4)) + [99, 99, 99, 99]) == [b1]  # divergence
    assert c.match([7, 7, 7, 7]) == []
    # refcount-1 entries (cache-only) are evictable once callers free
    a.free(b1), a.free(b2)
    assert c.evictable() == 2
    assert c.evict_one() is True  # LRU first
    assert len(c) == 1 and a.free_blocks == 6
    assert c.evict_one() is True and c.evict_one() is False


# -- token identity vs the contiguous/sequential reference -------------------

PROMPTS = [list(range(2, 2 + n)) for n in (4, 7, 3, 9, 5, 6)]
MAX_NEWS = [6, 3, 13, 5, 7, 9]


@pytest.mark.parametrize("horizon", [1, 4, 16])
def test_paged_greedy_token_identity(horizon):
    """The tentpole acceptance contract: paged decode with mid-stream
    joins is token-identical to sequential generate at H in {1,4,16}."""
    eng = _paged_engine(horizon=horizon)
    for i in range(3):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    eng.step()  # first block in flight
    for i in range(3, 6):  # join while a block is mid-pipeline
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    res = eng.run()
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} at horizon {horizon}"
        )
        assert res[f"r{i}"].outcome == "done"
    # every block went back to the pool once everything finished
    assert eng._balloc.allocated_blocks == 0


def test_paged_eos_mid_block():
    prompt = [5, 6, 7, 8]
    full = _sequential(prompt, 8)
    eos = full[2]  # mid-block at H=8
    eng = _paged_engine(max_slots=2, horizon=8)
    eng.submit("stops", prompt, 8, eos_id=eos)
    eng.submit("runs", [9, 10, 11], 6)
    res = eng.run()
    assert res["stops"].tokens == full[:3]
    assert res["stops"].outcome == "eos"
    assert res["runs"].tokens == _sequential([9, 10, 11], 6)


def test_paged_block_boundary_prompts():
    """Prompt lengths exactly at, one under, and one over a block
    boundary — the scatter/gather addressing edge cases."""
    cases = [(7, 9), (8, 8), (9, 7), (16, 5), (17, 4)]
    eng = _paged_engine(max_slots=2, block_size=8)
    for j, (plen, mn) in enumerate(cases):
        eng.submit(f"b{j}", list(range(2, 2 + plen)), mn)
    res = eng.run()
    for j, (plen, mn) in enumerate(cases):
        assert res[f"b{j}"].tokens == _sequential(
            list(range(2, 2 + plen)), mn
        ), f"prompt len {plen}"


def test_paged_deadline_evict_then_reuse():
    """Join/evict over the pool: a deadline eviction frees the slot's
    blocks mid-decode; a new request reuses the lane and pool without
    cross-request token leaks."""
    t = [0.0]
    eng = _paged_engine(max_slots=2, clock=lambda: t[0])
    eng.submit("slow", [1, 2, 3], 40, deadline_s=5.0)
    eng.submit("ok", [4, 5, 6], 4)
    for _ in range(3):
        eng.step()
    t[0] = 10.0  # past slow's deadline
    eng.step()
    eng.submit("next", [7, 8, 9, 10], 6)
    res = eng.run()
    assert res["slow"].outcome == "timeout"
    full = _sequential([1, 2, 3], 40)
    assert res["slow"].tokens == full[: len(res["slow"].tokens)]
    assert res["ok"].tokens == _sequential([4, 5, 6], 4)
    assert res["next"].tokens == _sequential([7, 8, 9, 10], 6)
    assert eng._balloc.allocated_blocks == 0


# -- prefix cache: shared blocks, CoW, skipped prefill ------------------------


def test_prefix_hit_skips_prefill_and_stays_identical():
    """A warm prefix-cache hit maps shared blocks instead of
    re-prefilling them: the dispatch counter proves the skip, the
    tokens prove correctness, and divergence past the shared prefix
    (different tails) stays isolated (copy-on-write territory)."""
    shared = list(range(2, 18))  # two full 8-blocks
    a = shared + [30, 31, 32]
    b = shared + [40, 41]
    eng = _paged_engine(max_slots=2, prefix_cache=True)
    eng.submit("a", a, 6)
    res = eng.run()
    assert res["a"].tokens == _sequential(a, 6)
    hits_before = eng._prefix.hits
    pf_before = eng.metrics.snapshot()["dispatches_prefill"]
    eng.submit("b", b, 6)
    res = eng.run()
    assert res["b"].tokens == _sequential(b, 6)
    assert eng._prefix.hits - hits_before == 2  # both shared blocks hit
    # exactly ONE prefill dispatch for b, covering only the tail — the
    # shared 16 tokens issued zero prefill work
    assert eng.metrics.snapshot()["dispatches_prefill"] - pf_before == 1


def test_full_prefix_hit_cow_divergence():
    """An IDENTICAL prompt (full-chain hit, length % block_size == 0)
    re-prefills only its last token into a copy-on-written block; both
    requests emit identical greedy streams and shared blocks survive
    for a third divergent request."""
    prompt = list(range(2, 26))  # 24 tokens = three full 8-blocks
    want = _sequential(prompt, 7)
    eng = _paged_engine(max_slots=3, prefix_cache=True)
    eng.submit("one", prompt, 7)
    res = eng.run()
    assert res["one"].tokens == want
    eng.submit("two", prompt, 7)  # full hit -> CoW of the last block
    eng.submit("three", prompt[:16] + [50] * 8, 5)  # diverges at block 2
    res = eng.run()
    assert res["two"].tokens == want
    assert res["three"].tokens == _sequential(prompt[:16] + [50] * 8, 5)
    assert eng._prefix.hits >= 5  # 3 (full) + 2 (partial)
    assert eng._balloc.allocated_blocks == len(eng._prefix)  # cache-only refs


def test_prefix_hit_counter_and_blocks_free_gauge():
    from edl_tpu.obs import memledger
    from edl_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.reset_default_registry()
    memledger.reset_default_ledger(reg)
    eng = _paged_engine(max_slots=2, prefix_cache=True)
    prompt = list(range(2, 18))
    eng.submit("a", prompt, 4)
    eng.run()
    eng.submit("b", prompt + [60, 61], 4)
    eng.run()
    c = reg.get("edl_kv_prefix_hit_total")
    assert c is not None and c.value() >= 2
    g = reg.get("edl_kv_blocks_free")
    assert g is not None and g.value() > 0
    occ = reg.get("edl_kv_occupancy_ratio")
    assert occ is not None  # block-aware path exercised
    memledger.reset_default_ledger()


# -- chunked prefill ----------------------------------------------------------


def test_chunked_prefill_token_identity_and_interleave():
    """A long prompt admitted as bounded chunks: tokens identical, and
    the chunk dispatches interleave with decode blocks instead of one
    monolithic prefill (prefill dispatch count goes UP, per chunk)."""
    long_p = list(range(2, 42))  # 40 tokens, chunk=8 -> 4 chunks + tail
    short = [3, 4, 5]
    eng = _paged_engine(max_slots=2, prefill_chunk=8, horizon=2)
    eng.submit("short", short, 12)
    eng.step()
    eng.submit("long", long_p, 6)
    res = eng.run()
    assert res["long"].tokens == _sequential(long_p, 6)
    assert res["short"].tokens == _sequential(short, 12)
    snap = eng.metrics.snapshot()
    # short: 1; long: 4 chunks + 1 final piece
    assert snap["dispatches_prefill"] == 6


def test_chunked_prefill_recovery_replays_inline():
    faults.arm("serve.dispatch:raise@n=2", seed=0)
    eng = _paged_engine(max_slots=2, prefill_chunk=8, horizon=4)
    long_p = list(range(2, 30))
    eng.submit("long", long_p, 8)
    eng.submit("short", [9, 9, 2], 6)
    res = eng.run()
    faults.disarm()
    assert res["long"].tokens == _sequential(long_p, 8)
    assert res["short"].tokens == _sequential([9, 9, 2], 6)
    assert eng.recoveries >= 1


# -- pool pressure: block-gated admission + preemption ------------------------


def test_admission_gates_on_blocks_not_slots():
    """A pool smaller than max_slots' worth of sequences admits by
    free blocks: everything still completes token-identically, with
    head-of-line FIFO preserved through requeues."""
    # usable pool = 8 blocks of 8 = 64 tokens; max_len 64 means one
    # full-length sequence fits, concurrency comes from short ones
    eng = _paged_engine(max_slots=4, block_size=8, pool_blocks=9)
    for i in range(6):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    res = eng.run()
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} under pool pressure"
        )
    assert eng._balloc.allocated_blocks == 0


def test_preemption_restores_and_completes():
    """Decode growth under a tight pool preempts the youngest slot
    back to the queue; the preempted request restarts and both emit
    exact greedy streams."""
    eng = _paged_engine(max_slots=2, block_size=8, pool_blocks=9,
                        max_len=64)
    eng.submit("deep", [1, 2, 3, 4], 44)  # grows to 6 blocks
    eng.submit("young", list(range(5, 21)), 20)  # 2 blocks + growth
    res = eng.run()
    assert res["deep"].tokens == _sequential([1, 2, 3, 4], 44)
    assert res["young"].tokens == _sequential(list(range(5, 21)), 20)
    assert eng._balloc.allocated_blocks == 0


# -- crash recovery rebuilds pool + tables ------------------------------------


@pytest.mark.parametrize("plan", [
    "serve.dispatch:raise@n=3",
    "serve.drain:raise@n=2",
    "serve.prefill:raise@n=2",
])
def test_paged_recovery_token_identity(plan):
    faults.arm(plan, seed=0)
    eng = _paged_engine(horizon=8, max_recoveries=3, prefix_cache=True)
    for i in range(3):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    eng.step()
    for i in range(3, 6):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    res = eng.run()
    faults.disarm()
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} under {plan}"
        )
    assert eng.recoveries >= 1
    # only the prefix cache's own refs remain once every slot freed
    assert eng._balloc.allocated_blocks == len(eng._prefix)


def test_recovery_rebuilds_consistent_tables():
    """After a crash the pool, allocator, and tables are rebuilt from
    host truth: live slots' tables cover exactly their resident tokens
    and reference only allocated blocks."""
    faults.arm("serve.dispatch:raise@n=2", seed=0)
    eng = _paged_engine(max_slots=2, horizon=4)
    eng.submit("a", PROMPTS[0], 20)
    eng.submit("b", PROMPTS[1], 20)
    for _ in range(3):
        eng.step()
    faults.disarm()
    assert eng.recoveries >= 1
    for i, sl in enumerate(eng._slots):
        if sl is None:
            continue
        resident = len(sl.prompt) + len(sl.generated)
        nb = paged.blocks_for(resident, eng.block_size)
        tbl = eng._tables[i]
        for j in range(nb):
            assert tbl[j] != paged.SCRATCH
            assert eng._balloc.refcount(tbl[j]) >= 1
    res = eng.run()
    assert res["a"].tokens == _sequential(PROMPTS[0], 20)
    assert res["b"].tokens == _sequential(PROMPTS[1], 20)


# -- donation + construction validation ---------------------------------------


def test_paged_pool_donated_in_place():
    eng = _paged_engine(max_slots=2)
    kc0 = eng._kc
    ptr0 = kc0.unsafe_buffer_pointer()
    eng.submit("a", [1, 2, 3], 6)
    eng.step()
    assert eng._donates is True
    assert kc0.is_deleted()
    assert eng._kc.unsafe_buffer_pointer() == ptr0  # genuinely in place


def test_paged_constructor_validation():
    with pytest.raises(ValueError, match="multiple"):
        ContinuousBatchingEngine(PARAMS, CFG, max_len=60, block_size=8)
    with pytest.raises(ValueError, match="pool_blocks"):
        ContinuousBatchingEngine(
            PARAMS, CFG, max_len=64, block_size=8, pool_blocks=4
        )
    with pytest.raises(ValueError, match="block_size"):
        ContinuousBatchingEngine(PARAMS, CFG, max_len=64, prefix_cache=True)
    with pytest.raises(ValueError, match="block_size"):
        ContinuousBatchingEngine(PARAMS, CFG, max_len=64, prefill_chunk=8)
