"""On-disk metric history (edl_tpu/obs/tsdb.py): round-trip, exact
downsampling, retention under a byte budget, counter-reset clamping,
the /history endpoint, and `edl watch --once --json` determinism over
a recorded directory. jax-free — the tsdb is stdlib-only."""

import json
import math

import pytest

from edl_tpu.cli.main import main as cli_main
from edl_tpu.obs import (
    TSDB,
    MetricsRegistry,
    scrape,
    series_key,
    snapshot_from_prometheus_text,
    start_exporter,
)
from edl_tpu.obs.metrics import percentile_from_buckets
from edl_tpu.obs.tsdb import flatten_snapshot, parse_series_key


def reg_with(value: float, count_v: float = 0.0) -> MetricsRegistry:
    r = MetricsRegistry()
    r.gauge("edl_test_gauge", "g").set(value)
    if count_v:
        r.counter("edl_test_total", "c").inc(count_v)
    return r


def test_series_key_roundtrip():
    key = series_key("edl_x", {"b": "2", "a": "1"})
    assert key == "edl_x{a=1,b=2}"  # sorted -> canonical
    assert parse_series_key(key) == ("edl_x", {"a": "1", "b": "2"})


def test_append_rejects_non_snapshot(tmp_path):
    db = TSDB(str(tmp_path / "h"))
    with pytest.raises(ValueError):
        db.append({"not": "a snapshot"}, t=1.0)


def test_append_accepts_snapshot_json_string(tmp_path):
    db = TSDB(str(tmp_path / "h"))
    r = reg_with(3.5)
    db.append(r.snapshot_json(), t=100.0)
    assert db.points("edl_test_gauge") == [(100.0, 3.5)]


def test_downsample_preserves_window_aggregates_exactly(tmp_path):
    """The acceptance pin: a closed 10s bucket carries the EXACT
    sum/cnt/min/max of the raw points inside it — downsampling loses
    resolution, never arithmetic."""
    db = TSDB(str(tmp_path / "h"))
    vals = [float(v) for v in (5, 1, 9, 4, 7, 2, 8, 3, 6, 0)]
    for i, v in enumerate(vals):
        r = MetricsRegistry()
        r.gauge("edl_test_gauge", "g").set(v)
        db.append(r.snapshot(), t=1000.0 + i)  # all inside [1000, 1010)
    db.append(reg_with(99.0).snapshot(), t=1011.0)  # closes the bucket

    recs = list(db._iter_tier(10.0, 1000.0, 1010.0))
    closed = [r for r in recs if r["t0"] == 1000.0]
    assert len(closed) == 1
    agg = closed[0]["series"][series_key("edl_test_gauge")]
    assert agg["sum"] == sum(vals)
    assert agg["cnt"] == len(vals)
    assert agg["min"] == min(vals)
    assert agg["max"] == max(vals)
    assert agg["last"] == vals[-1]

    # and the query path folds the same numbers back out
    buckets = db.series("edl_test_gauge", t0=1000.0, t1=1009.5, step=10.0)
    assert buckets[0]["sum"] == sum(vals)
    assert buckets[0]["avg"] == pytest.approx(sum(vals) / len(vals))


def test_retention_enforces_byte_budget_not_coverage(tmp_path):
    """Over budget, the oldest RAW segment goes first — the early
    window survives in the downsample tiers (degraded resolution,
    intact coverage)."""
    db = TSDB(
        str(tmp_path / "h"), segment_bytes=4096, max_bytes=24 << 10
    )
    for i in range(400):
        r = MetricsRegistry()
        r.gauge("edl_test_gauge", "g").set(float(i))
        db.append(r.snapshot(), t=1000.0 + 2.0 * i)
    db.flush()
    assert db.total_bytes() <= 24 << 10
    kinds = {k for _, k, _ in db._segments()}
    assert "raw" in kinds and "agg10" in kinds
    # earliest raw appends were retained out — but the tier still
    # answers for that window (points falls back to bucket `last`)
    assert db.raw_times()[0] > 1000.0
    early = db.points("edl_test_gauge", t0=1000.0, t1=1100.0)
    assert early, "retention must not create a coverage hole"


def test_counter_reset_clamps_increase(tmp_path):
    """increase() over a restarting counter: 5 -> 10 -> (restart) 3 ->
    4 is an increase of 9 (5 up, then 3 counted from zero, then 1) —
    never the naive negative delta."""
    db = TSDB(str(tmp_path / "h"))
    for i, v in enumerate((5.0, 10.0, 3.0, 4.0)):
        r = MetricsRegistry()
        r.counter("edl_test_total", "c").inc(v)
        db.append(r.snapshot(), t=100.0 + i)
    assert db.increase("edl_test_total") == 9.0
    assert db.increase("edl_test_total", t0=100.0, t1=101.0) == 5.0


def hist_reg(samples) -> MetricsRegistry:
    r = MetricsRegistry()
    h = r.histogram(
        "edl_test_seconds", "h", buckets=(0.1, 0.5, 1.0)
    )
    for s in samples:
        h.observe(s)
    return r


def test_hist_delta_windowed_percentiles(tmp_path):
    db = TSDB(str(tmp_path / "h"))
    db.append(hist_reg([0.05]).snapshot(), t=100.0)
    # one process accumulating: +3 fast, +1 slow in the window
    db.append(
        hist_reg([0.05, 0.05, 0.05, 0.05, 0.8]).snapshot(), t=110.0
    )
    d = db.hist_delta("edl_test_seconds", t0=99.0, t1=111.0)
    assert d["count"] == 4.0
    # delta = 3 in le=0.1, 1 in le=1.0: p50 within the fast bucket
    assert percentile_from_buckets(d["pairs"], 0.5) <= 0.1
    assert percentile_from_buckets(d["pairs"], 0.99) > 0.5


def test_hist_delta_restart_clamps_to_later_sample(tmp_path):
    """Total count DROPPED between window edges -> the process
    restarted; the later cumulative sample IS the window delta (no
    negative bucket counts, ever)."""
    db = TSDB(str(tmp_path / "h"))
    db.append(hist_reg([0.05] * 10).snapshot(), t=100.0)
    db.append(hist_reg([0.8, 0.8]).snapshot(), t=110.0)  # restarted
    d = db.hist_delta("edl_test_seconds", t0=99.0, t1=111.0)
    assert d["count"] == 2.0
    assert all(v >= 0.0 for _, v in d["pairs"])
    assert percentile_from_buckets(d["pairs"], 0.5) > 0.5


def test_series_single_bucket_when_step_none(tmp_path):
    db = TSDB(str(tmp_path / "h"))
    for i in range(5):
        r = MetricsRegistry()
        r.gauge("edl_test_gauge", "g").set(float(i))
        db.append(r.snapshot(), t=100.0 + i)
    buckets = db.series("edl_test_gauge", t0=100.0, t1=104.0)
    assert len(buckets) == 1  # the alert engine's whole-window read
    assert buckets[0]["cnt"] == 5.0
    assert buckets[0]["last"] == 4.0


def test_snapshot_from_prometheus_text_roundtrip(tmp_path):
    r = MetricsRegistry()
    r.gauge("edl_test_gauge", "g", ("cls",)).set(0.75, cls="a")
    snap = snapshot_from_prometheus_text(r.render())
    db = TSDB(str(tmp_path / "h"))
    db.append(snap, t=100.0)
    assert db.points("edl_test_gauge", {"cls": "a"}) == [(100.0, 0.75)]


def test_flatten_snapshot_splits_kinds():
    r = hist_reg([0.05])
    r.gauge("edl_test_gauge", "g").set(1.0)
    scalars, hists = flatten_snapshot(r.snapshot())
    assert series_key("edl_test_gauge") in scalars
    assert series_key("edl_test_seconds") in hists
    h = hists[series_key("edl_test_seconds")]
    assert h["count"] == 1.0 and len(h["counts"]) == len(h["buckets"]) + 1


def test_history_endpoint_over_live_exporter(tmp_path):
    db = TSDB(str(tmp_path / "h"))
    for i in range(3):
        r = MetricsRegistry()
        r.gauge("edl_test_gauge", "g").set(float(i))
        db.append(r.snapshot(), t=100.0 + i)
    exp = start_exporter(lambda: MetricsRegistry(), history=db)
    try:
        hz = json.loads(scrape(exp.url, "/healthz"))
        assert "/history" in hz["endpoints"]
        idx = json.loads(scrape(exp.url, "/history"))
        assert series_key("edl_test_gauge") in idx["series"]
        doc = json.loads(
            scrape(exp.url, "/history?name=edl_test_gauge")
        )
        assert doc["points"] == [[100.0, 0.0], [101.0, 1.0], [102.0, 2.0]]
        stepped = json.loads(scrape(
            exp.url, "/history?name=edl_test_gauge&t0=100&t1=103&step=10"
        ))
        assert stepped["points"][0]["sum"] == 3.0
    finally:
        exp.stop()


def test_history_404_without_store():
    exp = start_exporter(lambda: MetricsRegistry())
    try:
        hz = json.loads(scrape(exp.url, "/healthz"))
        assert "/history" not in hz["endpoints"]
        with pytest.raises(Exception):
            scrape(exp.url, "/history")
    finally:
        exp.stop()


def record_slo_dir(tmp_path, ratios):
    """A recorded directory with an interactive-TTFT ratio series —
    what a loadgen --tsdb-dir run leaves behind."""
    db = TSDB(str(tmp_path / "rec"))
    for i, v in enumerate(ratios):
        r = MetricsRegistry()
        r.gauge(
            "edl_slo_ttft_ok_ratio", "ok", ("slo_class",)
        ).set(v, slo_class="interactive")
        r.gauge("edl_slo_goodput_fraction", "gp").set(v)
        db.append(r.snapshot(), t=1000.0 + i)
    db.flush()
    return str(tmp_path / "rec")


def test_watch_once_json_is_deterministic(tmp_path, capsys):
    """Replaying the SAME recorded directory twice produces byte-equal
    summaries — the property the CI lane's assertions stand on."""
    rec = record_slo_dir(tmp_path, [1.0] * 30)
    rc1 = cli_main(["watch", rec, "--once", "--json"])
    out1 = capsys.readouterr().out
    rc2 = cli_main(["watch", rec, "--once", "--json"])
    out2 = capsys.readouterr().out
    assert (rc1, out1) == (rc2, out2)
    summary = json.loads(out1)
    assert summary["transitions"] == []
    assert summary["fired_total"] == 0
    assert rc1 == 0


def test_watch_replay_fires_and_exit_code_counts_pages(tmp_path, capsys):
    """A recorded outage (ratio collapses, stays down) fires the
    fast-burn page on replay, and `--once` exits with the page count."""
    rec = record_slo_dir(tmp_path, [1.0] * 5 + [0.0] * 25)
    rules = {
        "time_scale": 1.0,
        "rules": [{
            "type": "burn_rate", "name": "gp_fast",
            "series": "edl_slo_goodput_fraction", "labels": {},
            "objective": 0.95, "short_s": 3.0, "long_s": 30.0,
            "factor": 14.4, "severity": "page",
        }],
    }
    rp = tmp_path / "rules.json"
    rp.write_text(json.dumps(rules))
    rc = cli_main([
        "watch", rec, "--once", "--json", "--rules", str(rp),
    ])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 1  # one active page at end of replay
    assert summary["fired_total"] == 1
    assert summary["transitions"][0]["rule"] == "gp_fast"
    assert summary["transitions"][0]["transition"] == "fire"


def test_watch_events_out_chains_in_postmortem(tmp_path, capsys):
    """--events-out dumps the watch process's flight-recorder window;
    a fired-but-unresolved alert shows up as a postmortem problem."""
    from edl_tpu.obs import postmortem

    rec = record_slo_dir(tmp_path, [1.0] * 5 + [0.0] * 25)
    rules = {
        "time_scale": 1.0,
        "rules": [{
            "type": "burn_rate", "name": "gp_fast",
            "series": "edl_slo_goodput_fraction", "labels": {},
            "objective": 0.95, "short_s": 3.0, "long_s": 30.0,
            "factor": 14.4, "severity": "page",
        }],
    }
    rp = tmp_path / "rules.json"
    rp.write_text(json.dumps(rules))
    ev = tmp_path / "events.jsonl"
    cli_main([
        "watch", rec, "--once", "--json", "--rules", str(rp),
        "--events-out", str(ev),
    ])
    capsys.readouterr()
    events = postmortem.load_events(str(ev))
    problems = postmortem.verify_recovered(events, site_prefix="alert.")
    assert any("never resolved" in p for p in problems)
