"""Distributed chip-lease broker: the LeaseTable state machine, the
coordinator lease protocol (Python + native + wire), epoch fencing,
crash-safe persistence, and the DistributedChipBroker client adapter
driving the real ElasticityController."""

import json
import os
import threading
import time

import pytest

from edl_tpu.elasticity.broker import (
    FREED,
    GRANTED,
    RECALLING,
    LeaseError,
)
from edl_tpu.elasticity.controller import (
    ElasticityController,
    ServePort,
    TrainPort,
)
from edl_tpu.elasticity.distbroker import DistributedChipBroker
from edl_tpu.obs import events as flight
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.runtime import coordinator as coord_mod
from edl_tpu.runtime.coordinator import (
    CoordinatorClient,
    CoordinatorServer,
    PyCoordinator,
    ensure_native_built,
)
from edl_tpu.runtime.lease_table import LeaseTable
from edl_tpu.runtime.lease_table import FREED as T_FREED
from edl_tpu.runtime.lease_table import GRANTED as T_GRANTED
from edl_tpu.utils import faults

HAVE_NATIVE = ensure_native_built()


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# LeaseTable: the state machine behind every backend


def test_table_lifecycle_and_conservation():
    t = LeaseTable()
    assert t.init(8)
    g = t.grant("train:job", 6, token="t1")
    assert g["ok"] and g["epoch"] == 1
    assert t.check_conservation()
    assert t.recall(g["id"]) == "ok"
    assert t.recall(g["id"]) == "ok"  # idempotent while RECALLING
    assert t.free(g["id"]) == 6
    assert t.free(g["id"]) == -2  # already freed
    assert t.free(999) == -1  # unknown
    assert t.check_conservation()
    snap = t.snap()
    assert snap["free"] == 8 and snap["pool"] == 8


def test_table_grant_refusals_and_reinit():
    t = LeaseTable()
    assert t.grant("train:job", 2)["reason"] == "nopool"
    assert t.init(4)
    g = t.grant("train:job", 3, token="t1")
    assert t.grant("serve:r0", 2)["reason"] == "nochips"
    assert not t.init(8)  # live lease: re-init refused
    assert t.init(4)  # same total: idempotent
    t.recall(g["id"])
    t.free(g["id"])
    assert t.init(8)  # drained: resize allowed
    # epoch survives the re-init — fencing stays globally monotonic
    g2 = t.grant("serve:r0", 2, token="t2")
    assert g2["epoch"] > g["epoch"]


def test_table_token_idempotent_grant():
    """A retried LGRANT (reply lost) returns the ORIGINAL lease: no
    chips move, no epoch bump."""
    t = LeaseTable()
    t.init(8)
    g1 = t.grant("train:job", 4, token="tok-a")
    g2 = t.grant("train:job", 4, token="tok-a")
    assert g2 == g1
    assert t.snap()["free"] == 4  # granted once, not twice
    # a DIFFERENT token is a real second grant
    g3 = t.grant("train:job", 4, token="tok-b")
    assert g3["id"] != g1["id"] and t.snap()["free"] == 0


def test_table_confirm_fencing():
    t = LeaseTable()
    t.init(8)
    g = t.grant("serve:r0", 2, token="t1")
    assert t.confirm(g["id"], g["epoch"]) == "ok"
    assert t.confirm(g["id"], g["epoch"] - 1) == "stale_epoch"
    assert t.confirm(999, 1) == "unknown"
    t.recall(g["id"])
    t.free(g["id"])
    assert t.confirm(g["id"], g["epoch"]) == "freed"


def test_table_restore_recovery_window():
    """Restore → RECOVERING: free recomputed from first principles,
    live leases unconfirmed; re-confirmation ends recovery, silence
    past the window is force-released — exactly the silent holders."""
    clk = Clock()
    docs = []
    t = LeaseTable(persist=docs.append, clock=clk)
    t.init(8)
    g1 = t.grant("train:job", 4, token="t1")
    g2 = t.grant("serve:r0", 2, token="t2")

    t2 = LeaseTable(recover_window_s=5.0, clock=clk)
    t2.restore(docs[-1])
    assert t2.recovering
    assert t2.snap()["free"] == 2  # recomputed, not persisted
    assert t2.check_conservation()
    # inside the window: nothing reaped yet
    assert t2.expire() == (0, 1)
    # one holder re-confirms; the other stays silent
    assert t2.confirm(g1["id"], g1["epoch"]) == "ok"
    clk.t += 6.0
    released, recovering = t2.expire()
    assert (released, recovering) == (1, 0)
    assert not t2.recovering
    snap = {l["id"]: l for l in t2.snap()["leases"]}
    assert snap[g1["id"]]["state"] == T_GRANTED  # confirmed: survived
    assert snap[g2["id"]]["state"] == T_FREED  # silent: force-released
    assert t2.check_conservation() and t2.snap()["free"] == 4


def test_table_all_confirmed_ends_recovery_early():
    clk = Clock()
    docs = []
    t = LeaseTable(persist=docs.append, clock=clk)
    t.init(4)
    g = t.grant("train:job", 4, token="t1")
    t2 = LeaseTable(recover_window_s=100.0, clock=clk)
    t2.restore(docs[-1])
    assert t2.recovering
    assert t2.confirm(g["id"], g["epoch"]) == "ok"
    assert not t2.recovering  # no need to wait out the window


def test_table_conservation_across_persist_crash():
    """`lease.persist:raise@n=1`: the injected raise lands AFTER the
    doc is durably persisted but BEFORE the caller sees a reply — the
    lost-reply window. Conservation must hold across a restore from
    exactly that point, and the token retry must return the original
    lease instead of double-granting."""
    docs = []
    t = LeaseTable(persist=docs.append)
    t.init(8)
    faults.arm("lease.persist:raise@n=1,max=1")
    try:
        with pytest.raises(faults.InjectedFault):
            t.grant("train:job", 4, token="tok-a")
    finally:
        faults.disarm()
    # the broker process dies on the lost reply; a new one restores
    t2 = LeaseTable(recover_window_s=0.0)
    t2.restore(docs[-1])
    assert t2.check_conservation()
    assert t2.snap()["free"] == 4  # the grant WAS persisted
    # the caller never heard back and retries with the same token
    g = t2.grant("train:job", 4, token="tok-a")
    assert g["ok"] and g["chips"] == 4
    assert t2.snap()["free"] == 4  # absorbed, not double-granted
    assert t2.check_conservation()
    # and the retry re-confirmed the lease: recovery is over
    assert not t2.recovering


def test_table_crashed_holder():
    t = LeaseTable()
    t.init(8)
    t.grant("serve:r0", 2, token="a")
    t.grant("serve:r0", 2, token="b")
    t.grant("train:job", 2, token="c")
    assert t.crashed("serve:r0") == 4
    assert t.crashed("serve:r0") == 0  # idempotent
    assert t.snap()["free"] == 6 and t.check_conservation()


# ---------------------------------------------------------------------------
# PyCoordinator: lease table persisted through the KV


def test_pycoordinator_lease_restore_roundtrip():
    c1 = PyCoordinator()
    assert c1.lease_init(8)
    g = c1.lease_grant("train:job", 5, token="t1")
    assert g["ok"]
    # the broker restart analog: a fresh coordinator restores the
    # persisted doc from the KV
    c2 = PyCoordinator()
    c2.kv_put("lease/table", c1.kv_get("lease/table"))
    c2.lease_restore()
    c2.lease_set_recover_window(0.0)
    snap = c2.lease_snap()
    assert snap["recovering"] and snap["free"] == 3
    # the token retry re-confirms and recovery ends
    g2 = c2.lease_grant("train:job", 5, token="t1")
    assert g2["id"] == g["id"] and g2["epoch"] == g["epoch"]
    assert c2.lease_expire() == (0, 0)
    assert not c2.lease_snap()["recovering"]


# ---------------------------------------------------------------------------
# native + wire: WAL replay, restart recovery, fencing on the wire


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_native_lease_wal_replay_idempotent_grant(tmp_path):
    wal = str(tmp_path / "lease.wal")
    c = coord_mod.NativeCoordinator(10.0, wal_path=wal)
    assert c.lease_init(8)
    g = c.lease_grant("train:job", 4, token="tok-a")
    assert g["ok"]
    del c
    # replay: the restarted broker knows the lease AND its token, so a
    # duplicate LGRANT (client retry after the crash) is absorbed
    c2 = coord_mod.NativeCoordinator(10.0, wal_path=wal)
    snap = c2.lease_snap()
    assert snap["recovering"] and snap["free"] == 4
    g2 = c2.lease_grant("train:job", 4, token="tok-a")
    assert g2["id"] == g["id"] and g2["epoch"] == g["epoch"]
    assert c2.lease_snap()["free"] == 4  # not double-granted
    c2.lease_set_recover_window(0.0)
    assert c2.lease_expire() == (0, 0)  # retry re-confirmed it


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_wire_restart_fences_silent_holder(tmp_path):
    """Server SIGKILL + respawn: the re-confirming holder survives the
    RECOVERING window, the silent one is force-released, and its
    zombie LCONFIRM is FENCED."""
    srv = CoordinatorServer(
        port=0, wal_path=str(tmp_path / "w.wal"), lease_recover_s=0.0
    )
    cli = CoordinatorClient("127.0.0.1", srv.port)
    try:
        assert cli.lease_init(8)
        g1 = cli.lease_grant("train:job", 4, token="a")
        g2 = cli.lease_grant("serve:r0", 2, token="b")
        srv.kill()  # SIGKILL mid-conversation
        srv._spawn()  # respawn replays the WAL
        # the client's reconnect window absorbs the restart
        assert cli.lease_snap()["recovering"]
        assert cli.lease_confirm(g1["id"], g1["epoch"]) == "ok"
        released, recovering = cli.lease_expire()
        assert (released, recovering) == (1, 0)
        snap = cli.lease_snap()
        assert snap["free"] == 4 and not snap["recovering"]
        # conservation at the coordinator
        live = sum(l["chips"] for l in snap["leases"] if l["state"] != 2)
        assert live + snap["free"] == snap["pool"]
        # the force-released holder's zombie confirm is fenced
        assert cli.lease_confirm(g2["id"], g2["epoch"]) == "freed"
        # and a stale-epoch confirm on a LIVE lease is fenced too
        assert cli.lease_confirm(g1["id"], g1["epoch"] - 1) == "stale_epoch"
    finally:
        cli.close()
        srv.stop()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_wire_old_server_degrades_to_none():
    """The TIME pattern: lease ops against a server that answers
    'ERR unknown command' must come back None, not crash."""
    srv = CoordinatorServer(port=0)
    cli = CoordinatorClient("127.0.0.1", srv.port)
    try:
        # simulate an old binary by asking for an op that can't exist
        assert cli._call("LBOGUS 1") == "ERR unknown command"
        # and the real degradation contract on a genuinely unknown op:
        # the client maps "ERR unknown command" to None for lease ops
        # (covered end-to-end against real old servers by the version
        # gate in lease_* methods; here we pin the parse split)
        assert cli.lease_recall(12345) == "unknown"
    finally:
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# DistributedChipBroker: the ChipLeaseBroker-compatible adapter


def _dist(coord=None, chips=8):
    return DistributedChipBroker(
        coord or PyCoordinator(), chips, registry=MetricsRegistry()
    )


def test_distbroker_parity_lifecycle():
    flight.reset_default_recorder()
    b = _dist()
    lease = b.grant("train:job", 6)
    assert lease.state == GRANTED and lease.epoch == 1
    assert b.free_chips == 2 and b.check_conservation()
    r = b.recall(lease.lease_id)
    assert r.state == RECALLING
    again = b.recall(lease.lease_id)  # retried RPC: idempotent
    assert again.state == RECALLING
    assert b.free(lease.lease_id) == 6
    assert b.free(lease.lease_id) == 0
    assert b.get(lease.lease_id).state == FREED
    assert b.free_chips == 8
    # exactly one recall event despite the retry — broker parity
    evs = [e for e in flight.default_recorder().records()
           if e["kind"] == "lease.recall"]
    assert len(evs) == 1
    with pytest.raises(LeaseError, match="nochips"):
        b.grant("serve:r0", 9)
    with pytest.raises(LeaseError, match="unknown"):
        b.recall("L9999")


def test_distbroker_fence_event_and_counter():
    flight.reset_default_recorder()
    reg = MetricsRegistry()
    b = DistributedChipBroker(PyCoordinator(), 8, registry=reg)
    lease = b.grant("serve:r0", 2)
    assert b.confirm(lease.lease_id)
    # forge a stale holder: its remembered epoch predates the truth
    with b._lock:
        b._leases[lease.lease_id].epoch = lease.epoch - 1
    assert b.confirm(lease.lease_id) is False
    evs = [e for e in flight.default_recorder().records()
           if e["kind"] == "lease.fence"]
    assert len(evs) == 1
    assert evs[0]["attrs"]["reason"] == "stale_epoch"
    assert evs[0]["corr"]["site"] == "lease.confirm"
    fenced = reg.get("edl_lease_fenced_total")
    assert fenced is not None and fenced.value(reason="stale_epoch") == 1
    # the fenced mirror stops counting those chips locally
    assert b.get(lease.lease_id).state == FREED


def test_distbroker_resync_recovers_and_counts():
    flight.reset_default_recorder()
    reg = MetricsRegistry()
    c1 = PyCoordinator()
    b = DistributedChipBroker(c1, 8, registry=reg)
    b.grant("train:job", 5)
    # broker restart: fresh coordinator restores the persisted doc
    c2 = PyCoordinator()
    c2.kv_put("lease/table", c1.kv_get("lease/table"))
    c2.lease_restore()
    c2.lease_set_recover_window(0.0)
    b.coord = c2
    assert b.recovering
    res = b.resync()
    assert res["fenced"] == [] and not res["recovering"]
    assert b.check_conservation() and b.free_chips == 3
    evs = [e for e in flight.default_recorder().records()
           if e["kind"] == "lease.recover"]
    assert len(evs) == 1
    recoveries = reg.get("edl_lease_recoveries_total")
    assert recoveries is not None and recoveries.value() == 1


def test_distbroker_adopt_then_fenced():
    """The holder-restart path: a holder re-attaching with stale
    memory is fenced at confirm, not silently accepted."""
    b = _dist()
    lease = b.grant("serve:r0", 2)
    b2 = DistributedChipBroker(b.coord, 8, registry=MetricsRegistry())
    ok_lease = b2.adopt(lease.lease_id, lease.holder, lease.chips,
                        lease.epoch)
    assert b2.confirm(ok_lease.lease_id)  # correct memory: accepted
    stale = b2.adopt(lease.lease_id, lease.holder, lease.chips,
                     lease.epoch + 7)
    assert b2.confirm(stale.lease_id) is False  # stale memory: fenced


def test_distbroker_rpc_fault_site_raises_connectionerror(monkeypatch):
    """lease.rpc drop → ConnectionError, the type the controller's
    recall retry (and any holder loop) already handles."""
    b = _dist()
    faults.arm("lease.rpc:drop@n=1,max=1")
    try:
        with pytest.raises(ConnectionError):
            b.grant("train:job", 2)
    finally:
        faults.disarm()
    # nothing moved: the drop fired before the RPC
    assert b.free_chips == 8 and b.check_conservation()
    # the retry lands
    assert b.grant("train:job", 2).chips == 2


# ---------------------------------------------------------------------------
# the controller runs UNCHANGED against the distributed broker


def test_controller_handover_over_distbroker():
    """Full diurnal policy loop against the coordinator-fronted broker:
    same handovers as the in-process rehearsal, conservation after
    every tick, and a recall fault recovered through the controller's
    own retry."""
    flight.reset_default_recorder()
    clk = Clock()
    b = DistributedChipBroker(
        PyCoordinator(), 8, registry=MetricsRegistry(), clock=clk
    )
    state = {"train_chips": 6, "replicas": 1, "offered": 0.25}
    train = TrainPort(
        chips=lambda: state["train_chips"],
        apply_chips=lambda n: state.update(train_chips=n),
        min_chips=2,
    )
    serve = ServePort(
        replicas=lambda: state["replicas"],
        load=lambda: state["offered"] / max(state["replicas"], 1),
        slo_breached=lambda: False,
        add_replica=lambda: state.update(replicas=state["replicas"] + 1)
        or 0.0,
        remove_replica=lambda: state.update(replicas=state["replicas"] - 1),
        min_replicas=1,
    )
    ctl = ElasticityController(
        b, train, serve, chips_per_replica=2, cooldown_s=0.0,
        clock=clk, registry=MetricsRegistry(),
    )
    ctl.bootstrap()
    faults.arm("lease.recall:raise@n=1,max=1")  # first recall RPC dies
    try:
        actions = []
        for hour in range(26):
            clk.t = hour * 3600.0
            h = hour % 24
            state["offered"] = (
                6.0 if 10 <= h <= 17 else 2.0 if h in (8, 9, 18, 19)
                else 0.25
            )
            actions.append(ctl.tick())
            assert b.check_conservation(), f"conservation broke at {hour}"
    finally:
        faults.disarm()
    assert "to_serve" in actions and "to_train" in actions
    # the armed recall fault fired and the controller's retry closed it
    injected = [e for e in flight.default_recorder().records()
                if e["kind"] == "fault.injected"
                and e["corr"].get("site") == "lease.recall"]
    recovered = [e for e in flight.default_recorder().records()
                 if e["kind"] == "lease.recover"]
    assert injected and recovered


# ---------------------------------------------------------------------------
# client backoff: decorrelated jitter


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_client_backoff_decorrelated_jitter(monkeypatch):
    """Reconnect sleeps are drawn from [0.05, 3*prev) capped at 2 s —
    not the lockstep 0.05/0.1/0.2 doubling that would thundering-herd
    a restarted broker."""
    srv = CoordinatorServer(port=0)
    cli = CoordinatorClient("127.0.0.1", srv.port, reconnect_window_s=30.0)
    sleeps = []
    real_sleep = time.sleep
    monkeypatch.setattr(
        coord_mod.time, "sleep",
        lambda s: (sleeps.append(s), real_sleep(min(s, 0.01)))[1],
    )
    try:
        # five consecutive drops inside ONE call: the retry loop eats
        # them and sleeps between attempts, then the sixth attempt lands
        faults.arm("coord.rpc:drop@every=1,max=5")
        try:
            assert cli.ping()
        finally:
            faults.disarm()
        # the patch is global: drop sub-floor polling sleeps from other
        # threads (server wrapper) — backoff sleeps are always >= 0.05
        backoffs = [s for s in sleeps if s >= 0.05]
    finally:
        cli.close()
        srv.stop()
    assert len(backoffs) == 5
    assert all(s <= 2.0 for s in backoffs)
    # only the very first backoff is the deterministic floor; every
    # later one is a fresh uniform draw — identical values would mean
    # the decorrelated jitter is gone
    assert len(set(round(s, 6) for s in backoffs[1:])) > 1
