"""Randomized stress of the lockstep elastic protocol (VERDICT r1 #9).

The go/await-go/teardown state machine (runtime/worker_main.py) is the
correctness core of the multi-process runtime. The scenario tests in
test_multiproc.py each exercise ONE schedule; here a seeded RNG drives
an arbitrary interleaving of scale-up, scale-down (graceful SIGTERM
drain), and SIGKILL fault injection against a running job, and asserts
the invariants that must hold under EVERY schedule:

  - the job drains to ``phase == succeeded`` within a timeout (no
    stranded-collective hang — the failure mode this hunt targets);
  - every worker that was not hard-killed exits 0;
  - sample accounting is exactly-once-ish: at completion the lease
    queue shows every task acked (done == total), nothing still
    leased/todo, nothing dead (reference analog: the master task
    queue's re-dispatch guarantee, docker/paddle_k8s:28-31).

Reference has no analog of this test (its elastic demo is manual,
doc/boss_tutorial.md); the fake-pod scheduler here is what SURVEY §4
calls "multi-node without a cluster".
"""

import os
import random
import signal
import time

import pytest

# real worker subprocesses + live timing: run serially
# (scripts/run_tests.sh); CPU contention flakes these in-suite
pytestmark = pytest.mark.multiproc

from edl_tpu.runtime.launcher import ProcessJobLauncher

N_SAMPLES = 6144
CHUNK = 32  # per_device_batch(32) x local_devices(1): one task per step-row-set

# CI runs 3 seeds per shape; EDL_FUZZ_SEEDS=N widens the sweep for a
# dedicated soak (e.g. EDL_FUZZ_SEEDS=20 python -m pytest tests/test_fuzz_elastic.py)
SEEDS = list(range(int(os.environ.get("EDL_FUZZ_SEEDS", "3"))))


@pytest.mark.parametrize("seed", SEEDS)
def test_randomized_kill_scale_schedule(tmp_path, seed):
    rng = random.Random(1000 + seed)
    with ProcessJobLauncher(
        job=f"fz{seed}",
        model="linreg",
        min_workers=1,
        max_workers=4,
        n_samples=N_SAMPLES,
        passes=1,
        per_device_batch=CHUNK,
        step_sleep_s=0.05,
        member_ttl_s=2.0,
        lease_timeout_s=3.0,
        # virtual 2-worker slices: the slice-kill arm below can take an
        # entire slice down at once (multi-slice fault coverage)
        workers_per_slice=2,
        # tight WAL compaction so the soak crosses snapshot+truncate
        # cycles (incl. across the coord-restart arm)
        wal_compact_bytes=32 * 1024,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        events = []
        drained = set()
        for _ in range(3):
            # let training advance between events so faults land at
            # random protocol phases (mid-epoch, near barriers, ...)
            try:
                launcher.wait_progress(launcher.progress() + 2, timeout_s=180)
            except RuntimeError:
                break  # job already drained
            live = sorted(launcher.live_workers(), key=lambda w: w.worker_id)
            if not live:
                break
            roll = rng.random()
            if roll < 0.3 and len(live) >= 2:
                # hard-kill anyone but the senior worker (the senior
                # SIGKILL case has a dedicated scenario test; keeping
                # one un-killed worker makes completion well-defined
                # under every schedule)
                victim = rng.choice(live[1:]).worker_id
                events.append(("kill", victim))
                launcher.kill(victim)
            elif roll < 0.5 and len(live) >= 2:
                # compound fault: scale, then kill INSIDE the reshard
                # window (rendezvous / dist re-init / restore) — the
                # protocol phases a lone scale event never lands on
                n = rng.randint(2, 4)
                time.sleep(rng.random())  # land at a random phase
                drained.update(launcher.scale_to(n))
                time.sleep(rng.random() * 0.5)
                # victim pool excludes drained workers: a mid-drain
                # process may exit between snapshot and kill (KeyError)
                live2 = sorted(
                    (
                        w
                        for w in launcher.live_workers()
                        if w.worker_id not in drained
                    ),
                    key=lambda w: w.worker_id,
                )
                if len(live2) >= 2:
                    victim = rng.choice(live2[1:]).worker_id
                    events.append(("scale+kill", n, victim))
                    try:
                        launcher.kill(victim)
                    except KeyError:
                        events[-1] = ("scale", n)  # victim exited first
                else:
                    events.append(("scale", n))
            elif roll < 0.65:
                # back-to-back retargets: the second supersedes the
                # first before its reshard settles
                a, b = rng.randint(1, 4), rng.randint(1, 4)
                events.append(("scale2", a, b))
                drained.update(launcher.scale_to(a))
                time.sleep(rng.random() * 0.5)
                drained.update(launcher.scale_to(b))
            elif roll < 0.8:
                # coordinator death: SIGKILL the coordination plane
                # itself mid-protocol and restart it — the WAL must
                # restore exact membership/queue state and worker
                # clients must ride out the outage on reconnect backoff
                events.append(("coord-restart",))
                launcher.kill_coordinator()
                time.sleep(rng.random() * 0.5)
                launcher.restart_coordinator()
            elif roll < 0.9:
                n = rng.randint(1, 4)
                events.append(("scale", n))
                drained.update(launcher.scale_to(n))
            else:
                # whole-slice outage: SIGKILL every live worker on one
                # slice at once (a preempted v5e slice), sparing the
                # senior worker's slice so completion stays well-defined
                senior_slice = launcher._slice_of(live[0].worker_id)
                other = sorted(
                    {launcher._slice_of(w.worker_id) for w in live}
                    - {senior_slice}
                )
                if other:
                    victims = launcher.kill_slice(other[-1])
                    events.append(("slice-kill", other[-1], tuple(victims)))
                else:
                    n = rng.randint(1, 4)
                    events.append(("scale", n))
                    drained.update(launcher.scale_to(n))
        rcs = launcher.wait(timeout_s=420)

        killed = set()
        for ev in events:
            if ev[0] == "kill":
                killed.add(ev[1])
            elif ev[0] == "scale+kill":
                killed.add(ev[2])
            elif ev[0] == "slice-kill":
                killed.update(ev[2])
        sigterm = -signal.SIGTERM
        for w, rc in rcs.items():
            if w in killed:
                continue
            if w in drained:
                # drained workers exit 0; a SIGTERM that lands during
                # interpreter startup (before any handler can exist)
                # kills raw — benign, the worker never joined
                assert rc in (0, sigterm), (seed, events, w, launcher.log_tail(w, 4000))
            else:
                assert rc == 0, (seed, events, w, launcher.log_tail(w, 4000))
        assert launcher.kv("phase") == "succeeded", (seed, events)

        stats = launcher.client.queue_stats()
        expected = -(-N_SAMPLES // CHUNK)  # ceil
        assert stats["done"] == expected, (seed, events, stats)
        assert stats["todo"] == 0 and stats["leased"] == 0, (seed, events, stats)
        assert stats["dead"] == 0, (seed, events, stats)
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
        # WAL stays O(state) under every schedule: bytes appended since
        # the last snapshot never exceed the threshold by more than the
        # snapshot itself (the exact accounting above held ACROSS those
        # snapshot+truncate cycles — and across coordinator restarts)
        wal_bytes = os.path.getsize(str(tmp_path / "coordinator.wal"))
        assert wal_bytes < 128 * 1024, (seed, events, wal_bytes)
        assert launcher.client.wal_stats()["appended_bytes"] <= wal_bytes
