"""Scheduler spec — the reference's autoscaler test suite, ported case by
case (reference: pkg/autoscaler_internal_test.go:96-438) onto the TPU
resource model: GPU limits become TPU chips, node idle maps gain free
chips. Under the default flexible slice policy the algorithm must match
the reference step for step. TPU-only additions (pow2 slice policy,
chip-aware host search) are at the bottom.
"""

from edl_tpu.api.job import TrainingJob, TrainingJobSpec, WorkerSpec
from edl_tpu.api.resources import ResourceRequirements, ResourceSpec
from edl_tpu.cluster import topology
from edl_tpu.cluster.base import WorkerGroup
from edl_tpu.cluster.resource import ClusterResource, Hosts
from edl_tpu.scheduler.autoscaler import (
    JobState,
    elastic,
    needs_chips,
    scale_all_jobs_dry_run,
    scale_dry_run,
    sorted_jobs,
)


def make_job(name, cpu_req, mem_req, chips, lo, hi, parallelism) -> JobState:
    """reference: makeJob autoscaler_internal_test.go:56-94."""
    res = ResourceRequirements(
        requests=ResourceSpec(cpu_milli=cpu_req, mem_mega=mem_req, tpu_chips=chips),
        limits=ResourceSpec(cpu_milli=cpu_req, mem_mega=mem_req, tpu_chips=chips),
    )
    job = TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            worker=WorkerSpec(min_replicas=lo, max_replicas=hi, resources=res)
        ),
    )
    group = WorkerGroup(
        name=f"{name}-worker", namespace="default", plan=None, parallelism=parallelism
    )
    return JobState(config=job, group=group)


def all_idle_hosts() -> Hosts:
    """reference: allIdleNodes autoscaler_internal_test.go:109-112."""
    return Hosts(
        cpu_idle_milli={"host0": 99999},
        mem_free_mega={"host0": 99999},
        chips_free={"host0": 99999},
    )


def test_trainer_request_limit():
    # reference: TestTrainerRequestLimit :96-101 (quantity math is covered
    # in test_job.py; here the JobState accessors)
    j = make_job("name", 1_000_000, 105, 8, 1, 1, 1)
    assert j.cpu_request_milli() == 1_000_000
    assert j.mem_request_mega() == 105
    assert j.chips_per_worker() == 8


def test_scale_dry_run_satisfied():
    # reference: TestScaleDryRunSatisfied :103-107
    r = ClusterResource(cpu_total_milli=2000, mem_total_mega=1000)
    j = make_job("name", 1000, 100, 0, 1, 2, 2)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_more_cpu():
    # reference: TestScaleDryRunMoreCPU :114-126
    r = ClusterResource(
        cpu_limit_milli=100,
        cpu_request_milli=100,
        cpu_total_milli=3000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 1


def test_scale_dry_run_no_more_cpu():
    # reference: TestScaleDryRunNoMoreCPU :128-141
    r = ClusterResource(
        cpu_limit_milli=1000,
        cpu_request_milli=1000,
        cpu_total_milli=1000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_more_chips():
    # reference: TestScaleDryRunMoreGPU :143-159
    r = ClusterResource(
        cpu_total_milli=2000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_limit=0,
        chip_request=0,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 10, 1, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 1
    # "should not scale up if the scale down parameter is true"
    r2 = ClusterResource(
        cpu_total_milli=2000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    assert scale_dry_run(r2, j, 0, 1.0, True) == 0


def test_scale_dry_run_no_more_chips():
    # reference: TestScaleDryRunNoMoreGPU :161-177
    r = ClusterResource(
        cpu_total_milli=2000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 10, 1, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_down_more_than_expected():
    # reference: TestScaleDryRunScaleDownMoreThanExpected :179-197
    # parallelism 6 over max 3: -1 per step until planned == max.
    r = ClusterResource(
        cpu_limit_milli=1000,
        cpu_request_milli=1000,
        cpu_total_milli=1000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
    )
    j = make_job("name", 1000, 10, 0, 1, 3, 6)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    assert scale_dry_run(r, j, -1, 1.0, True) == -1
    assert scale_dry_run(r, j, -2, 1.0, True) == -1
    assert scale_dry_run(r, j, -3, 1.0, True) == 0


def test_scale_down_to_min():
    # reference: TestScaleDryRunScaleDownToMin :199-217
    # cluster CPU over target load: -1 until min.
    r = ClusterResource(
        cpu_limit_milli=5000,
        cpu_request_milli=5000,
        cpu_total_milli=3000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 10, 0, 1, 3, 3)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    assert scale_dry_run(r, j, -1, 1.0, True) == -1
    assert scale_dry_run(r, j, -2, 1.0, True) == 0


def test_scale_down_full_cluster():
    # reference: TestScaleDryRunScaleDownFullCluster :219-236
    r = ClusterResource(
        cpu_limit_milli=2000,
        cpu_request_milli=2000,
        cpu_total_milli=1000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 10, 0, 1, 3, 3)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    # "should not scale down if the scale down parameter is false"
    r2 = ClusterResource(
        cpu_limit_milli=2000,
        cpu_request_milli=2000,
        cpu_total_milli=1000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    assert scale_dry_run(r2, j, 0, 1.0, False) == 0


def test_scale_dry_run_no_mem():
    # reference: TestScaleDryRunNoMem :238-254
    r = ClusterResource(
        cpu_limit_milli=1000,
        cpu_request_milli=1000,
        cpu_total_milli=1000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_limit=10,
        chip_request=10,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_all_dry_run_no_mem():
    # reference: TestScaleAllDryRunNoMem :256-269
    r = ClusterResource(
        cpu_total_milli=1000,
        mem_request_mega=1000,
        mem_limit_mega=1000,
        mem_total_mega=1000,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 1, 1, 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["name"] == 0


def test_scale_all_dry_run():
    # reference: TestScaleAllDryRun :271-288 — scale 1 → 3 (+2)
    r = ClusterResource(
        cpu_limit_milli=1000,
        cpu_request_milli=1000,
        cpu_total_milli=4000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_limit=8,
        chip_request=8,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["name"] == 2


def test_scale_all_dry_run_not_full():
    # reference: TestScaleAllDryRunNotFull :290-307 — maxLoad 0.8 caps at +1
    r = ClusterResource(
        cpu_limit_milli=1000,
        cpu_request_milli=1000,
        cpu_total_milli=3000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 0.8)["name"] == 1


def test_scale_all_dry_run_down_not_full():
    # reference: TestScaleAllDryRunDownNotFull :309-326 — over 0.8 load → -1
    r = ClusterResource(
        cpu_limit_milli=3000,
        cpu_request_milli=3000,
        cpu_total_milli=3000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 100, 0, 1, 3, 3)
    assert scale_all_jobs_dry_run([j], r, 0.8)["name"] == -1


def test_scale_all_dry_run_less_cpu():
    # reference: TestScaleAllDryRunLessCPU :328-345 — CPU bounds at +1
    r = ClusterResource(
        cpu_limit_milli=2000,
        cpu_request_milli=2000,
        cpu_total_milli=3000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_limit=8,
        chip_request=8,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 1, 1, 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["name"] == 1


def test_scale_all_dry_run_less_chips():
    # reference: TestScaleAllDryRunLessGPU :347-364 — chips bound at +1
    r = ClusterResource(
        cpu_limit_milli=990,
        cpu_request_milli=990,
        cpu_total_milli=2000,
        mem_request_mega=100,
        mem_limit_mega=100,
        mem_total_mega=1000,
        chip_limit=9,
        chip_request=9,
        chip_total=10,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1, 1, 1, 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["name"] == 1


def test_fulfillment():
    # reference: TestFulfillment :366-375
    assert make_job("n", 1, 1, 1, 1, 2, 2).fulfillment() == 1.0
    assert make_job("n", 1, 1, 1, 1, 2, 1).fulfillment() == 0.0
    assert make_job("n", 1, 1, 1, 1, 3, 2).fulfillment() == 0.5


def test_sorted_jobs():
    # reference: TestSortedJobs :377-398 (d filtered: not elastic)
    js = [
        make_job("a", 1, 1, 1, 1, 2, 2),
        make_job("b", 1, 1, 1, 1, 20, 2),
        make_job("c", 1, 1, 1, 1, 10, 2),
        make_job("d", 1, 1, 1, 1, 1, 2),
    ]
    assert [j.config.name for j in sorted_jobs(js, elastic)] == ["b", "c", "a"]


def test_sorted_jobs_chips_only():
    # reference: TestSortedJobsGPUOnly :400-420
    js = [
        make_job("a", 1, 1, 1, 1, 2, 2),
        make_job("b", 1, 1, 0, 1, 20, 2),
        make_job("c", 1, 1, 0, 1, 10, 2),
        make_job("d", 1, 1, 0, 1, 1, 2),
    ]
    assert [j.config.name for j in sorted_jobs(js, needs_chips)] == ["a"]


def test_sorted_jobs_with_tie():
    # reference: TestSortedJobsWithTie :422-438 — fulfillment ties broken by
    # chips asc, then CPU request asc, then memory request asc.
    js = [
        make_job("a", 1, 1, 1, 1, 2, 1),
        make_job("b", 1, 1, 0, 1, 2, 1),
        make_job("c", 10, 1, 0, 1, 2, 1),
        make_job("d", 1, 2, 0, 1, 2, 1),
    ]
    assert [j.config.name for j in sorted_jobs(js, elastic)] == ["b", "d", "c", "a"]


# ---------------------------------------------------------------------------
# TPU-only behavior (no reference analog)
# ---------------------------------------------------------------------------


def test_chip_aware_host_search():
    # A host with CPU/mem room but no free chips must not accept a
    # chip worker (the reference's searchAssignableNode is chip-blind).
    r = ClusterResource(
        cpu_total_milli=99999,
        mem_total_mega=99999,
        chip_total=8,
        hosts=Hosts(
            cpu_idle_milli={"h0": 99999},
            mem_free_mega={"h0": 99999},
            chips_free={"h0": 0},
        ),
    )
    j = make_job("name", 1, 1, 4, 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_pow2_slice_policy_up():
    # pow2 policy: 2 → 4 is one step of +2, and the resource guard must
    # cover the whole step.
    r = ClusterResource(
        cpu_total_milli=99999,
        mem_total_mega=99999,
        chip_total=16,
        chip_limit=8,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1, 1, 4, 1, 8, 2)
    assert scale_dry_run(r, j, 0, 1.0, False, policy=topology.pow2) == 2
    # only 1 chip headroom: the +2 step (8 chips) must be refused entirely
    r2 = ClusterResource(
        cpu_total_milli=99999,
        mem_total_mega=99999,
        chip_total=9,
        chip_limit=8,
        hosts=all_idle_hosts(),
    )
    j2 = make_job("name", 1, 1, 4, 1, 8, 2)
    assert scale_dry_run(r2, j2, 0, 1.0, False, policy=topology.pow2) == 0


def test_pow2_slice_policy_down():
    # Over target load, pow2 steps 4 → 2 (delta -2), not -1.
    r = ClusterResource(
        cpu_request_milli=5000,
        cpu_total_milli=3000,
        mem_total_mega=99999,
        chip_total=32,
        chip_limit=16,
        hosts=all_idle_hosts(),
    )
    j = make_job("name", 1000, 1, 4, 1, 8, 4)
    assert scale_dry_run(r, j, 0, 1.0, True, policy=topology.pow2) == -2


def test_pow2_step_spreads_over_hosts():
    # A +2 step of 4-chip workers on 4-chip hosts must claim TWO hosts,
    # not double-charge one.
    r = ClusterResource(
        cpu_total_milli=32000,
        mem_total_mega=64000,
        chip_total=16,
        chip_limit=8,
        hosts=Hosts(
            cpu_idle_milli={f"h{i}": 8000 for i in range(4)},
            mem_free_mega={f"h{i}": 16000 for i in range(4)},
            chips_free={"h0": 0, "h1": 0, "h2": 4, "h3": 4},
        ),
    )
    j = make_job("name", 500, 100, 4, 1, 8, 2)
    assert scale_dry_run(r, j, 0, 1.0, False, policy=topology.pow2) == 2
    assert r.hosts.chips_free["h2"] == 0
    assert r.hosts.chips_free["h3"] == 0
    # same step with only ONE free host: refused entirely
    r2 = ClusterResource(
        cpu_total_milli=32000,
        mem_total_mega=64000,
        chip_total=16,
        chip_limit=12,
        hosts=Hosts(
            cpu_idle_milli={f"h{i}": 8000 for i in range(4)},
            mem_free_mega={f"h{i}": 16000 for i in range(4)},
            chips_free={"h0": 0, "h1": 0, "h2": 0, "h3": 4},
        ),
    )
    assert scale_dry_run(r2, j, 0, 1.0, False, policy=topology.pow2) == 0


def test_over_max_lands_on_legal_count():
    # pow2 with an illegal max (6): from 8, walk down past 6 to legal 4.
    r = ClusterResource(cpu_total_milli=99999, mem_total_mega=99999, chip_total=99)
    j = make_job("name", 1, 1, 0, 1, 6, 8)
    d1 = scale_dry_run(r, j, 0, 1.0, True, policy=topology.pow2)
    assert d1 == -1  # 8 -> 7, still above max
    d2 = scale_dry_run(r, j, -1, 1.0, True, policy=topology.pow2)
    assert d2 == -3  # 7 -> 4 (6 and 5 are illegal)
    assert not topology.pow2(0)


def test_next_legal():
    assert topology.next_legal(2, 1, topology.pow2, 1, 8) == 4
    assert topology.next_legal(4, -1, topology.pow2, 1, 8) == 2
    assert topology.next_legal(8, 1, topology.pow2, 1, 8) == 8  # no legal above
    assert topology.next_legal(3, 1, topology.flexible, 1, 8) == 4


# -- slice-topology depth (VERDICT r1 #5) ------------------------------------


def make_accel_job(name, accel, chips, lo, hi, parallelism, cpu=1000, mem=1000):
    j = make_job(name, cpu, mem, chips, lo, hi, parallelism)
    j.config.spec.accelerator_type = accel
    return j


def _blocked_fleet(n_pods, hosts_per_pod, cpu=16000, mem=32000, chips=4):
    """A fleet of physical pods: hosts carry ici block + index."""
    hosts = Hosts()
    r = ClusterResource()
    for p in range(n_pods):
        for i in range(hosts_per_pod):
            name = f"p{p}h{i}"
            hosts.cpu_idle_milli[name] = cpu
            hosts.mem_free_mega[name] = mem
            hosts.chips_free[name] = chips
            hosts.ici_block[name] = f"pod{p}"
            hosts.ici_index[name] = i
            r.cpu_total_milli += cpu
            r.mem_total_mega += mem
            r.chip_total += chips
    r.hosts = hosts
    return r


def test_family_slice_catalogs():
    # v5e (2D torus): pow2 host counts capped at the 16x16-chip pod
    v5e = topology.slice_policy("v5e")
    assert topology.slice_host_counts("v5e") == [1, 2, 4, 8, 16, 32, 64]
    assert not v5e(128)  # beyond the largest v5e pod
    assert not v5e(6)
    # v4/v5p (3D torus): much larger cap
    assert topology.slice_policy("v4")(128)
    assert topology.slice_host_counts("v4")[-1] == 1024
    # canonical chip-grid names
    assert topology.topology_name("v5e", 2) == "2x4"
    assert topology.topology_name("v5e", 8) == "4x8"
    assert topology.topology_name("v5e", 64) == "16x16"
    assert topology.topology_name("v5e", 6) == ""
    assert topology.topology_name("v4", 16) == "4x4x4"


def test_policy_for_job_resolution():
    assert topology.policy_for_job("cpu", 0) is topology.flexible
    assert topology.policy_for_job("", 4) is topology.flexible
    assert topology.policy_for_job("v5e", 0) is topology.flexible
    p = topology.policy_for_job("v5e", 4)
    assert isinstance(p, topology.SliceShapePolicy)
    assert p.cap == 64 and p.contiguous


def test_v5e_and_dcn_jobs_each_respect_own_legality():
    """The VERDICT done-criterion: under the "auto" policy a v5e job and
    a flexible DCN job coexist — the v5e job only takes pow2 counts via
    contiguous windows, the DCN job takes any count anywhere."""
    r = _blocked_fleet(n_pods=2, hosts_per_pod=4)
    # add DCN-only (blockless) cpu hosts for the flexible job
    for i in range(3):
        name = f"dcn{i}"
        r.hosts.cpu_idle_milli[name] = 16000
        r.hosts.mem_free_mega[name] = 32000
        r.hosts.chips_free[name] = 0
        r.cpu_total_milli += 16000
        r.mem_total_mega += 32000

    tpu = make_accel_job("tpu", "v5e", 4, 1, 8, 1)
    web = make_accel_job("web", "cpu", 0, 1, 3, 1)
    diff = scale_all_jobs_dry_run([tpu, web], r, 1.0, "auto")
    # v5e job lands on a legal slice count (8 hosts available => 8)
    assert 1 + diff["tpu"] in topology.slice_host_counts("v5e")
    assert 1 + diff["tpu"] == 8
    # the flexible job grew without pow2 constraints
    assert 1 + diff["web"] == 3


def test_contiguity_blocks_fragmented_growth():
    """Free capacity that is NOT an aligned window must not satisfy an
    ICI job: 4 free hosts spread 2+2 across two pods can't make a
    4-host slice, but a flexible job takes them happily."""
    r = _blocked_fleet(n_pods=2, hosts_per_pod=4)
    # occupy hosts so each pod has exactly 2 free, misaligned: indices
    # 1,2 free in pod0; 0,3 free in pod1
    for name in ("p0h0", "p0h3", "p1h1", "p1h2"):
        r.hosts.chips_free[name] = 0
    tpu = make_accel_job("tpu", "v5e", 4, 2, 4, 2)
    tpu.group.parallelism = 2
    diff = scale_all_jobs_dry_run([tpu], r.copy(), 1.0, "auto")
    assert diff.get("tpu", 0) == 0  # no aligned window of 4 anywhere

    # pod1 indices 0..3 all free => aligned window exists => growth
    r2 = _blocked_fleet(n_pods=2, hosts_per_pod=4)
    for name in ("p0h0", "p0h3"):
        r2.hosts.chips_free[name] = 0
    diff2 = scale_all_jobs_dry_run([tpu], r2, 1.0, "auto")
    assert diff2.get("tpu", 0) == 2  # 2 -> 4 via pod1's aligned window


def test_contiguous_window_alignment():
    """Windows must start at index % n == 0 (sub-slice carving): a run
    of 2 free hosts at indices 1-2 is contiguous but misaligned."""
    from edl_tpu.scheduler.autoscaler import search_assignable_hosts

    r = _blocked_fleet(n_pods=1, hosts_per_pod=4)
    r.hosts.chips_free["p0h0"] = 0
    r.hosts.chips_free["p0h3"] = 0
    tpu = make_accel_job("t", "v5e", 4, 0, 4, 0)
    assert search_assignable_hosts(r, tpu, 2, contiguous=True) is None
    r.hosts.chips_free["p0h3"] = 4  # indices 2,3 free: aligned window
    assert search_assignable_hosts(r, tpu, 2, contiguous=True) == [
        "p0h2",
        "p0h3",
    ]
