"""Hardware-efficiency cost model (edl_tpu/obs/costmodel.py):

* the formula-dedup pin — bench.py, exp_mfu's peak lookup, and
  models/llama.py must all agree with the shared cost model on the r05
  flagship config (incl. the PUBLISHED 5637.1 MFLOPs/token figure);
* ground truth — analytic FLOPs vs XLA's own
  ``lower(...).cost_analysis()["flops"]`` for the train step and the
  decode-horizon block (tolerance-gated; skipped when the build's
  cost_analysis is unavailable);
* device-peak table semantics + env overrides;
* the EfficiencyMeter gauges and compile-watch behavior (first-call
  timing, obs.recompile only after warmup);
* the ElasticTrainer live-MFU wiring (flops_per_example ->
  edl_mfu{phase="train"}).
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edl_tpu.models import llama
from edl_tpu.obs import compilewatch
from edl_tpu.obs import costmodel as cm
from edl_tpu.obs import events as flight
from edl_tpu.obs import metrics as om


@pytest.fixture(autouse=True)
def _fresh_warmup():
    compilewatch.reset()
    yield
    compilewatch.reset()


def flagship_cfg():
    import bench

    return bench.flagship_train_config()


# ---------------------------------------------------------------------------
# formula dedup (ISSUE 8 satellite: three call sites, one formula)


def test_llama_train_flops_delegates_and_pins_published_figure():
    cfg = flagship_cfg()
    ours = cm.train_flops_per_token(cfg, 2048)
    assert llama.train_flops_per_token(cfg, 2048) == ours
    # BENCH_r02..r05 published llama_flops_per_token = 5637.1 MFLOPs
    assert round(ours / 1e6, 1) == 5637.1


def test_bench_decode_step_bytes_delegates():
    import bench

    cfg = bench.flagship_decode_config()
    pb = 2 * cm.n_params(cfg)  # bf16 export
    for b, s in ((1, 704), (8, 704), (32, 704)):
        assert bench._decode_step_bytes(cfg, pb, b, s) == cm.decode_step_bytes(
            cfg, pb, b, s
        )
    # the KV term is exactly the bench's original formula
    kv = 2 * cfg.n_layers * 8 * 704 * cfg.n_kv_heads * cfg.head_dim * 2
    assert cm.decode_step_bytes(cfg, pb, 8, 704) == pb + kv


def test_peak_table_matches_bench_values():
    import bench

    class D:
        def __init__(self, kind):
            self.device_kind = kind

    for kind, fl, bw in (
        ("TPU v5 lite", 197e12, 819e9),
        ("TPU v5e", 197e12, 819e9),
        ("TPU v5p", 459e12, 2765e9),
        ("TPU v5", 459e12, 2765e9),
        ("TPU v4", 275e12, 1228e9),
        ("TPU v6e", 918e12, 1640e9),
        ("weird-backend", 197e12, 819e9),  # conservative default
    ):
        assert bench._peak_flops(D(kind)) == fl, kind
        assert bench._peak_hbm_bw(D(kind)) == bw, kind
        assert cm.peak_for_kind(kind).flops == fl
        assert cm.peak_for_kind(kind).hbm_bytes_s == bw


def test_detect_peak_env_override(monkeypatch):
    monkeypatch.setenv("EDL_PEAK_TFLOPS", "123")
    monkeypatch.setenv("EDL_PEAK_HBM_GBS", "456")
    p = cm.detect_peak()
    assert p.flops == 123e12
    assert p.hbm_bytes_s == 456e9
    assert p.kind.endswith("+env")


def test_moe_activated_flops_counts_topk_not_all_experts():
    from edl_tpu.models.moe import MoEConfig

    dense_like = MoEConfig(n_experts=1, top_k=1)
    moe = MoEConfig(n_experts=8, top_k=2)
    # activated (per-token) params scale the ffn term by top_k=2 …
    assert cm.matmul_params(moe) < 3 * cm.matmul_params(dense_like)
    # … while the at-rest state counts ALL 8 experts
    assert cm.n_params(moe) > 6 * cm.n_params(dense_like) / 2
    ctr = cm.ctr_train_flops_per_example()
    assert ctr > 0 and math.isfinite(ctr)


# ---------------------------------------------------------------------------
# ground truth: XLA's own cost analysis (CPU; tolerance-gated)


def _xla_flops(lowered):
    try:
        ca = lowered.compile().cost_analysis()
    except Exception:  # noqa: BLE001 - capability probe, skip below
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    v = ca.get("flops")
    return float(v) if v and math.isfinite(v) and v > 0 else None


def test_train_flops_vs_xla_cost_analysis():
    # n_layers=1: jax's cost_analysis counts a lax.scan BODY once,
    # independent of trip count, so the layer scan must have trip
    # count 1 for the comparison to be apples-to-apples
    import dataclasses

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab=512), n_layers=1)
    B, T = 2, 64
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    loss_fn = llama.make_loss_fn(cfg)
    batch = {"tokens": jnp.zeros((B, T + 1), jnp.int32)}

    def fwd_bwd(p, b):
        return jax.value_and_grad(loss_fn)(p, b)

    flops = _xla_flops(jax.jit(fwd_bwd).lower(params, batch))
    if flops is None:
        pytest.skip("cost_analysis unavailable on this jax build")
    analytic = B * T * cm.train_flops_per_token(cfg, T)
    ratio = analytic / flops
    # the analytic model counts matmul+attention model FLOPs; XLA adds
    # norms/rope/softmax/CE and its per-op accounting differs in small
    # ways — the gate pins scale and exponents, not the last few %
    assert 0.6 < ratio < 1.5, (analytic, flops, ratio)


def test_decode_block_flops_vs_xla_cost_analysis():
    # horizon=1 for the same scan-body-counted-once reason; the layer
    # loop inside decode_step_slots is UNROLLED, so L=2 is fine here
    cfg = llama.LlamaConfig.tiny(vocab=512)
    B, S, H = 2, 32, 1
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    kvh, hd, L = cfg.n_kv_heads, cfg.head_dim, cfg.n_layers

    def block(p, tok, pos, active, rem, eosv, kc, vc):
        return llama.decode_horizon_slots(
            p, tok, pos, active, rem, eosv, kc, vc, cfg, horizon=H
        )

    args = (
        params,
        jnp.zeros(B, jnp.int32),
        jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool),
        jnp.full((B,), 8, jnp.int32),
        jnp.full((B,), -1, jnp.int32),
        jnp.zeros((L, B, S, kvh, hd), jnp.float32),
        jnp.zeros((L, B, S, kvh, hd), jnp.float32),
    )
    flops = _xla_flops(jax.jit(block).lower(*args))
    if flops is None:
        pytest.skip("cost_analysis unavailable on this jax build")
    analytic = cm.CostModel(cfg, peak=cm.peak_for_kind("v5e")).decode_block(
        B, H, S
    ).flops
    ratio = analytic / flops
    assert 0.6 < ratio < 1.5, (analytic, flops, ratio)


def test_int8_kv_decode_block_flops_and_bytes_vs_xla():
    """The quantized-KV paged decode program prices like the float one
    on FLOPs (dequant is a few multiplies against the matmul bill) —
    pinned against XLA's own cost_analysis — while the analytic BYTE
    ledger takes the KV dtype width + scale planes into account."""
    cfg = llama.LlamaConfig.tiny(vocab=512)
    B, H, bs, nb, M = 2, 1, 8, 9, 4  # S = M*bs = 32
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    kvh, hd, L = cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    hdp = llama.kvq_packed_head_dim("int8", hd)

    def block(p, tok, pos, table, kc, vc, ks, vs):
        return llama.decode_step_slots_paged(
            p, tok, pos, table, kc, vc, cfg, bs,
            kv_quant="int8", ks=ks, vs=vs,
        )

    args = (
        params,
        jnp.zeros(B, jnp.int32),
        jnp.zeros(B, jnp.int32),
        jnp.zeros((B, M), jnp.int32),
        jnp.zeros((L, nb, bs, kvh, hdp), jnp.int8),
        jnp.zeros((L, nb, bs, kvh, hdp), jnp.int8),
        jnp.zeros((L, nb, kvh), jnp.float32),
        jnp.zeros((L, nb, kvh), jnp.float32),
    )
    S = M * bs
    model = cm.CostModel(
        cfg, peak=cm.peak_for_kind("v5e"),
        kv_bytes_per_el=1.0, kv_block_size=bs,
    )
    flops = _xla_flops(jax.jit(block).lower(*args))
    if flops is None:
        pytest.skip("cost_analysis unavailable on this jax build")
    ratio = model.decode_block(B, H, S).flops / flops
    assert 0.6 < ratio < 1.5, (model.decode_block(B, H, S).flops, flops)
    # the byte ledger: int8 KV reads half the float figure + scales
    b_int8 = model.decode_block(B, H, S).hbm_bytes
    b_f = cm.CostModel(cfg, peak=cm.peak_for_kind("v5e")).decode_block(
        B, H, S
    ).hbm_bytes
    assert b_int8 < b_f
    assert b_int8 == H * cm.decode_step_bytes(
        cfg, model.param_bytes, B, S,
        kv_bytes_per_el=1.0, kv_block_size=bs,
    )


# ---------------------------------------------------------------------------
# EfficiencyMeter


def test_efficiency_meter_publishes_ratio_gauges():
    reg = om.MetricsRegistry()
    peak = cm.DevicePeak("test", 1e12, 1e11)
    meter = cm.EfficiencyMeter(peak, registry=reg)
    meter.observe("decode", cm.Cost(flops=5e11, hbm_bytes=5e10), seconds=1.0)
    assert reg.get("edl_mfu").value(phase="decode") == pytest.approx(0.5)
    assert reg.get("edl_bw_util_ratio").value(phase="decode") == pytest.approx(0.5)
    # cumulative: another second at zero work halves the rates
    meter.observe("decode", cm.Cost(0.0, 0.0), seconds=1.0)
    assert reg.get("edl_mfu").value(phase="decode") == pytest.approx(0.25)
    assert reg.get("edl_costmodel_flops_total").value(phase="decode") == 5e11
    # non-positive time is ignored, not a divide-by-zero
    meter.observe("decode", cm.Cost(1.0, 1.0), seconds=0.0)
    assert reg.get("edl_costmodel_flops_total").value(phase="decode") == 5e11
    meter.set_rates("train", 2.5e11, 2.5e10)
    assert reg.get("edl_mfu").value(phase="train") == pytest.approx(0.25)


def test_efficiency_snapshot_flattens_gauges():
    reg = om.MetricsRegistry()
    meter = cm.EfficiencyMeter(cm.DevicePeak("t", 1e12, 1e11), registry=reg)
    meter.set_rates("decode", 1e11, 1e10)
    snap = cm.efficiency_snapshot(reg)
    assert snap["mfu_decode"] == pytest.approx(0.1)
    assert snap["bw_util_decode"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# compile watch


def test_compilewatch_times_first_call_only_and_flags_recompiles():
    reg = om.reset_default_registry()
    rec = flight.default_recorder()
    rec.clear()
    calls = []

    def fn(x):
        calls.append(x)
        return x + 1

    w = compilewatch.wrap(fn, "test.prog")
    assert w(1) == 2 and w(2) == 3 and w(3) == 4
    hist = reg.get("edl_compile_seconds")
    assert hist.stats(program="test.prog")["count"] == 1
    assert reg.get("edl_compiles_total").value(program="test.prog") == 1
    # warmup not yet declared over: no recompile events
    kinds = [r["kind"] for r in rec.records()]
    assert "obs.recompile" not in kinds
    # a NEW program compiled after mark_warm lands on the timeline
    compilewatch.mark_warm()
    w2 = compilewatch.wrap(fn, "test.prog2")
    w2(1)
    evs = [r for r in rec.records() if r["kind"] == "obs.recompile"]
    assert len(evs) == 1
    assert evs[0]["attrs"]["program"] == "test.prog2"
    assert evs[0]["severity"] == "warn"
    # already-compiled programs stay silent
    w(4)
    assert len(
        [r for r in rec.records() if r["kind"] == "obs.recompile"]
    ) == 1
    om.reset_default_registry()


# ---------------------------------------------------------------------------
# trainer wiring: live train MFU


def test_elastic_trainer_publishes_train_mfu():
    import optax

    from edl_tpu.obs import memledger
    from edl_tpu.runtime.elastic import ElasticTrainer

    reg = om.reset_default_registry()
    # the default ledger binds its gauges at construction — pair the
    # registry swap with a ledger swap so they publish together
    memledger.reset_default_ledger(reg)
    try:
        cfg = llama.LlamaConfig.tiny(vocab=64)
        seq = 16
        trainer = ElasticTrainer(
            llama.make_loss_fn(cfg),
            optax.adam(1e-3),
            chips_per_worker=1,
            per_chip_batch=2,
            flops_per_example=seq * cm.train_flops_per_token(cfg, seq),
            hbm_bytes_per_example=cm.train_step_bytes(cfg, seq),
        )
        rng = np.random.RandomState(0)
        trainer.start(llama.init_params(jax.random.PRNGKey(0), cfg), 1)
        trainer.train_steps(
            lambda b: llama.synthetic_tokens(rng, b, seq, cfg.vocab), 2
        )
        assert reg.get("edl_mfu").value(phase="train") > 0
        assert reg.get("edl_bw_util_ratio").value(phase="train") > 0
        # the ledger carries the trainer's state
        assert reg.get("edl_hbm_bytes").value(category="params") > 0
        assert reg.get("edl_hbm_bytes").value(category="opt") > 0
    finally:
        memledger.reset_default_ledger(om.reset_default_registry())
