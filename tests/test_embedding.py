"""embedding_lookup: the sorted block-matmul backward must be exact
against the plain scatter-add for every id distribution, including the
adversarial ones that trigger the second window and the full fallback
(reference workload: the shared CTR embedding table,
example/ctr/ctr/train.py:46-64)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import edl_tpu.ops.embedding as emb
from edl_tpu.ops.embedding import embedding_lookup


def _grad_pair(table, ids, ct_dtype=jnp.float32):
    """(custom bwd, reference scatter bwd) for sum(lookup * w)."""
    w = jnp.asarray(
        np.random.RandomState(7).randn(*ids.shape, table.shape[1])
    ).astype(ct_dtype)

    def loss_custom(t):
        return jnp.sum(embedding_lookup(t, ids).astype(ct_dtype) * w)

    def loss_ref(t):
        return jnp.sum(jnp.take(t, ids, axis=0).astype(ct_dtype) * w)

    return jax.grad(loss_custom)(table), jax.grad(loss_ref)(table)


def _check(vocab, e, ids, tol=2e-5, dtype=jnp.float32):
    table = jnp.asarray(
        np.random.RandomState(0).randn(vocab, e).astype(np.float32)
    ).astype(dtype)
    got, ref = _grad_pair(table, ids)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=tol
    )


def test_forward_matches_take(cpu_devices):
    table = jnp.asarray(np.random.RandomState(0).randn(100, 8), jnp.float32)
    ids = jnp.asarray([[3, 7], [99, 0]], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(embedding_lookup(table, ids)),
        np.asarray(jnp.take(table, ids, axis=0)),
    )


def test_small_n_uses_plain_path_exact(cpu_devices):
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 500, 64), jnp.int32)
    _check(500, 8, ids)


def test_fast_path_uniform_ids(cpu_devices, monkeypatch):
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 256)
    ids = jnp.asarray(
        np.random.RandomState(2).randint(0, 4096, 1000), jnp.int32
    )
    _check(4096, 16, ids)


def test_fast_path_zipf_duplicates(cpu_devices, monkeypatch):
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 256)
    rng = np.random.RandomState(3)
    ids = jnp.asarray(
        np.minimum(rng.zipf(1.3, 1000) - 1, 4095).astype(np.int32)
    )
    _check(4096, 16, ids)


def test_fast_path_second_window(cpu_devices, monkeypatch):
    """Each block spans just under two windows: window two must fire
    and must not double-count rows at the vocab-end clamp."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 128)
    rng = np.random.RandomState(4)
    # ids clustered so a sorted 64-row block spans ~200 vocab (>128, <256)
    base = np.repeat(np.arange(0, 4096, 200), 49)[:1000]
    ids = jnp.asarray(
        np.minimum(base + rng.randint(0, 190, 1000), 4095).astype(np.int32)
    )
    _check(4096, 16, ids)


def test_fast_path_vocab_end_clamp(cpu_devices, monkeypatch):
    """All ids piled at the end of vocab: both windows clamp to
    vocab - TV; rows must be counted exactly once."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 128)
    rng = np.random.RandomState(5)
    ids = jnp.asarray(rng.randint(4096 - 140, 4096, 1000).astype(np.int32))
    _check(4096, 16, ids)


def test_adversarial_span_falls_back(cpu_devices, monkeypatch):
    """A block spanning > 2 windows must take the scatter fallback and
    stay exact."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 128)
    rng = np.random.RandomState(6)
    ids = jnp.asarray(rng.randint(0, 4096, 1000).astype(np.int32))
    # uniform over 4096 with 64-row blocks spans ~4096 >> 256: fallback
    _check(4096, 16, ids)


def test_bf16_table_close_to_f32_scatter(cpu_devices, monkeypatch):
    """bf16 table: our f32 accumulation is at least as accurate as the
    scatter (which accumulates in bf16), so compare against the f32
    reference with bf16 rounding tolerance."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 256)
    rng = np.random.RandomState(8)
    ids = jnp.asarray(rng.randint(0, 4096, 1000).astype(np.int32))
    table = jnp.asarray(rng.randn(4096, 16), jnp.float32)
    got_bf16, _ = _grad_pair(table.astype(jnp.bfloat16), ids)
    _, ref_f32 = _grad_pair(table, ids)
    np.testing.assert_allclose(
        np.asarray(got_bf16, np.float32),
        np.asarray(ref_f32, np.float32),
        atol=0.25,  # one bf16 ulp of the accumulated sums
    )


def test_out_of_range_ids_do_not_corrupt_valid_rows(cpu_devices, monkeypatch):
    """A stray negative / too-large id (data-pipeline padding sentinel)
    must not shift the gradient of the other rows in its sort block; the
    op clamps OOB ids to [0, V-1] in both directions."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 128)
    vocab, e = 4096, 16
    rng = np.random.RandomState(10)
    good = rng.randint(0, 130, 998).astype(np.int32)  # one narrow window
    ids = jnp.asarray(np.concatenate([[-5, 5000], good]).astype(np.int32))
    table = jnp.asarray(rng.randn(vocab, e).astype(np.float32))
    got, _ = _grad_pair(table, ids)
    clamped = jnp.clip(ids, 0, vocab - 1)
    _, ref = _grad_pair(table, clamped)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5
    )


def test_padding_does_not_force_fallback(cpu_devices, monkeypatch):
    """n not a multiple of BLOCK_ROWS with all ids far below vocab-1:
    the pad rows must not stretch the last block's span into the `bad`
    fallback. Detected by checking the fast path stays exact AND cheap —
    here simply that results match with ids confined to one window
    (the old vocab-1 padding made (last - vstart) >= 2*TV)."""
    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 128)
    rng = np.random.RandomState(11)
    ids = jnp.asarray(rng.randint(0, 100, 1000).astype(np.int32))  # 1000 % 64 != 0
    _check(4096, 16, ids)
    # the regression was vocab-1 padding flipping `bad` at runtime:
    # recompute the flag exactly as _blocked_grad does, with real-id pad
    n, bn, tv = 1000, 64, 128
    npad = -(-n // bn) * bn
    sids = np.sort(np.asarray(ids))
    sids = np.concatenate([sids, np.full(npad - n, sids[-1])])
    blocks = sids.reshape(-1, bn)
    vstart = np.minimum(blocks[:, 0], 4096 - tv)
    assert not np.any((blocks[:, -1] - vstart) >= 2 * tv)


def test_under_jit_and_dp_mesh(cpu_devices, monkeypatch):
    """The op must compile and stay exact inside a pjit'd train step on
    the virtual mesh (the bench path)."""
    import optax

    from edl_tpu.models import ctr
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.train.trainer import (
        TrainState,
        global_batch,
        make_train_step,
        shard_state,
    )

    monkeypatch.setattr(emb, "MIN_FAST_IDS", 1)
    monkeypatch.setattr(emb, "BLOCK_ROWS", 64)
    monkeypatch.setattr(emb, "VOCAB_WINDOW", 256)
    plan = MeshPlan.data_parallel(8)
    mesh = plan.build()
    params = ctr.init_params(jax.random.PRNGKey(0), vocab=2048, emb=8)
    tx = optax.adam(1e-2)
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    step = make_train_step(ctr.loss_fn, tx, plan, mesh)
    rng = np.random.RandomState(9)
    for _ in range(3):
        b = ctr.synthetic_batch(rng, 256, vocab=2048)
        state, m = step(state, global_batch(b, plan, mesh))
    assert np.isfinite(float(m["loss"]))


def test_sharded_lookup_matches_plain(cpu_devices):
    """Vocab-sharded lookup over a dp×tp mesh: forward and table
    gradient must match the single-table op."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from edl_tpu.ops.embedding import sharded_embedding_lookup
    from edl_tpu.parallel.mesh import MeshPlan

    plan = MeshPlan.create(dp=2, tp=4)
    mesh = plan.build()
    vocab, e = 512, 8
    rng = np.random.RandomState(12)
    table = jnp.asarray(rng.randn(vocab, e).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, (16, 26)).astype(np.int32))
    w = jnp.asarray(rng.randn(16, 26, e).astype(np.float32))

    table_s = jax.device_put(table, NamedSharding(mesh, P("tp", None)))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P("dp", None)))
    w_s = jax.device_put(w, NamedSharding(mesh, P("dp", None, None)))

    def loss_sharded(t):
        out = sharded_embedding_lookup(
            t, ids_s, mesh, "tp", ids_pspec=P("dp", None)
        )
        return jnp.sum(out * w_s)

    def loss_plain(t):
        return jnp.sum(embedding_lookup(t, ids) * w)

    out = jax.jit(
        lambda t: sharded_embedding_lookup(
            t, ids_s, mesh, "tp", ids_pspec=P("dp", None)
        )
    )(table_s)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(embedding_lookup(table, ids)), atol=1e-6
    )
    g_sharded = jax.jit(jax.grad(loss_sharded))(table_s)
    g_plain = jax.grad(loss_plain)(table)
    np.testing.assert_allclose(
        np.asarray(g_sharded), np.asarray(g_plain), atol=2e-5
    )


def test_sharded_lookup_rejects_ragged_vocab(cpu_devices):
    from edl_tpu.ops.embedding import sharded_embedding_lookup
    from edl_tpu.parallel.mesh import MeshPlan

    mesh = MeshPlan.create(tp=8).build()
    with pytest.raises(ValueError):
        sharded_embedding_lookup(
            jnp.zeros((100, 4)), jnp.zeros((2,), jnp.int32), mesh, "tp"
        )
