"""Alert engine (edl_tpu/obs/alerts.py): threshold/burn-rate/anomaly
rules over a recorded history, the fire/resolve state machine with
for_s debounce, flight-recorder + gauge observability of transitions,
postmortem alert chains, and the shipped DEFAULT_RULES doc. jax-free."""

import json
import math

import pytest

from edl_tpu.obs import TSDB, MetricsRegistry, alerts, postmortem
from edl_tpu.obs.alerts import (
    DEFAULT_RULES,
    AlertEngine,
    AnomalyRule,
    BurnRateRule,
    ThresholdRule,
    engine_from_doc,
    load_rules_doc,
    parse_rules,
)
from edl_tpu.obs.events import FlightRecorder
from edl_tpu.obs.metrics import ensure_core_series


def db_with_gauge(tmp_path, name, values, t0=1000.0, dt=1.0,
                  labels=None, labelnames=()):
    db = TSDB(str(tmp_path / "h"))
    for i, v in enumerate(values):
        r = MetricsRegistry()
        r.gauge(name, "g", tuple(labelnames)).set(v, **(labels or {}))
        db.append(r.snapshot(), t=t0 + i * dt)
    return db


# ---------------------------------------------------------------------------
# threshold


def test_threshold_fire_and_resolve_with_events_and_gauges(tmp_path):
    rec = FlightRecorder()
    reg = MetricsRegistry()
    engine = AlertEngine(
        [ThresholdRule("hot", "edl_temp", op=">", value=5.0,
                       window_s=10.0, agg="max", severity="page")],
        registry=reg, recorder=rec,
    )
    db = db_with_gauge(tmp_path, "edl_temp", [1.0, 2.0, 9.0])

    trs = engine.evaluate(db, 1002.5)
    assert [t["transition"] for t in trs] == ["fire"]
    assert engine.pages() == 1
    assert engine.active()[0]["value"] == 9.0

    # the window slides past the spike -> resolve
    trs = engine.evaluate(db, 1020.0)
    assert [t["transition"] for t in trs] == ["resolve"]
    assert engine.active() == []

    kinds = [e["kind"] for e in rec.records()]
    assert kinds == ["alert.fire", "alert.resolve"]
    fire = rec.records()[0]
    assert fire["corr"]["site"] == "alert.hot"
    assert fire["severity"] == "error"  # a page is an incident error

    fams = {f["name"] for f in reg.snapshot()["families"]}
    assert "edl_alerts_active" in fams
    assert "edl_alerts_fired_total" in fams
    text = reg.render()
    assert 'edl_alerts_active{severity="page"} 0' in text
    assert 'edl_alerts_fired_total{rule="hot"} 1' in text


def test_threshold_empty_window_never_fires(tmp_path):
    engine = AlertEngine(
        [ThresholdRule("hot", "edl_temp", op=">", value=0.0)]
    )
    db = TSDB(str(tmp_path / "h"))
    assert engine.evaluate(db, 1000.0) == []
    assert engine.active() == []


def test_for_s_debounce_requires_sustained_condition(tmp_path):
    engine = AlertEngine(
        [ThresholdRule("hot", "edl_temp", op=">", value=5.0,
                       window_s=5.0, for_s=3.0)]
    )
    db = db_with_gauge(tmp_path, "edl_temp", [9.0] * 20)
    assert engine.evaluate(db, 1001.0) == []  # pending, not fired
    assert engine.evaluate(db, 1002.0) == []
    trs = engine.evaluate(db, 1004.5)  # held > for_s
    assert [t["transition"] for t in trs] == ["fire"]


# ---------------------------------------------------------------------------
# burn rate


def burn_db(tmp_path, ratios, t0=1000.0):
    return db_with_gauge(
        tmp_path, "edl_slo_goodput_fraction", ratios, t0=t0
    )


def test_burn_rate_requires_both_windows(tmp_path):
    """A short blip trips the SHORT window but not the LONG one — no
    page (the whole point of the multi-window shape)."""
    rule = BurnRateRule(
        "gp", "edl_slo_goodput_fraction", objective=0.95,
        short_s=3.0, long_s=30.0, factor=14.4,
    )
    engine = AlertEngine([rule])
    # 28 clean samples, 2 bad: short window burns, long window doesn't
    db = burn_db(tmp_path, [1.0] * 28 + [0.0] * 2)
    assert engine.evaluate(db, 1029.0) == []

    # sustained breach: both windows above factor -> fire
    db2 = burn_db(tmp_path / "b", [1.0] * 5 + [0.0] * 25)
    trs = engine.evaluate(db2, 1029.0)
    assert [t["transition"] for t in trs] == ["fire"]
    assert trs[0]["burn_short"] > 14.4 and trs[0]["burn_long"] > 14.4


def test_burn_rate_resolves_when_recent_window_is_clean(tmp_path):
    rule = BurnRateRule(
        "gp", "edl_slo_goodput_fraction", objective=0.95,
        short_s=3.0, long_s=30.0, factor=14.4,
    )
    engine = AlertEngine([rule])
    # outage then recovery: the short window goes clean first
    db = burn_db(tmp_path, [0.0] * 20 + [1.0] * 10)
    assert [t["transition"] for t in engine.evaluate(db, 1015.0)] == ["fire"]
    trs = engine.evaluate(db, 1029.0)
    assert [t["transition"] for t in trs] == ["resolve"]
    assert trs[0]["active_s"] == pytest.approx(14.0)


def test_burn_rate_validation():
    with pytest.raises(ValueError):
        BurnRateRule("r", "edl_x", objective=1.5)
    with pytest.raises(ValueError):
        BurnRateRule("r", "edl_x", short_s=600.0, long_s=300.0)


def test_time_scale_shrinks_every_window(tmp_path):
    """time_scale=0.01 turns the production 300s/3600s pair into
    3s/36s — the same rules file drives the CI replay lane."""
    doc = {
        "time_scale": 0.01,
        "rules": [{
            "type": "burn_rate", "name": "gp",
            "series": "edl_slo_goodput_fraction",
            "objective": 0.95, "short_s": 300.0, "long_s": 3600.0,
            "factor": 14.4, "severity": "page",
        }],
    }
    engine = engine_from_doc(doc)
    rule = engine.rules[0]
    assert rule.short_s == pytest.approx(3.0)
    assert rule.long_s == pytest.approx(36.0)
    db = burn_db(tmp_path, [1.0] * 5 + [0.0] * 25)
    assert [t["transition"] for t in engine.evaluate(db, 1029.0)] == ["fire"]


# ---------------------------------------------------------------------------
# anomaly


def test_anomaly_fires_on_spike_not_on_flat(tmp_path):
    rule = AnomalyRule("an", "edl_temp", mode="value", window_s=100.0,
                       z=8.0, min_points=12)
    engine = AlertEngine([rule])
    flat = db_with_gauge(tmp_path / "flat", "edl_temp", [5.0] * 20)
    assert engine.evaluate(flat, 1019.5) == []  # band floor holds

    spiky = db_with_gauge(
        tmp_path / "spiky", "edl_temp", [5.0] * 19 + [500.0]
    )
    trs = engine.evaluate(spiky, 1019.5)
    assert [t["transition"] for t in trs] == ["fire"]
    assert trs[0]["robust_z"] > 8.0


def test_anomaly_needs_min_points(tmp_path):
    rule = AnomalyRule("an", "edl_temp", mode="value", window_s=100.0,
                       z=1.0, min_points=12)
    engine = AlertEngine([rule])
    db = db_with_gauge(tmp_path, "edl_temp", [5.0, 5.0, 500.0])
    assert engine.evaluate(db, 1002.5) == []  # too few samples to judge


def test_anomaly_increase_mode_survives_counter_reset(tmp_path):
    """Per-step increases are reset-clamped, so a process restart is a
    normal-sized step (its post-reset count), not the giant negative
    outlier a naive delta would produce."""
    db = TSDB(str(tmp_path / "h"))
    # cumulative counter stepping +1/+2 alternately, restarting at 2:
    # clamped increases stay in the 1..2 family across the restart
    vals = [0.0, 1.0, 3.0, 4.0, 6.0, 7.0, 9.0, 10.0, 12.0, 13.0,
            15.0, 2.0, 3.0, 5.0, 6.0, 8.0]
    for i, v in enumerate(vals):
        r = MetricsRegistry()
        r.counter("edl_test_total", "c").inc(v)
        db.append(r.snapshot(), t=1000.0 + i)
    rule = AnomalyRule("an", "edl_test_total", mode="increase",
                       window_s=100.0, z=8.0, min_points=12)
    engine = AlertEngine([rule])
    assert engine.evaluate(db, 1015.5) == []


# ---------------------------------------------------------------------------
# doc parsing / defaults


def test_parse_rules_rejects_bad_docs():
    with pytest.raises(ValueError, match="unknown rule type"):
        parse_rules({"rules": [{"type": "nope", "name": "r",
                                "series": "edl_x"}]})
    with pytest.raises(ValueError, match="duplicate rule name"):
        parse_rules({"rules": [
            {"type": "threshold", "name": "r", "series": "edl_x"},
            {"type": "threshold", "name": "r", "series": "edl_y"},
        ]})
    with pytest.raises(ValueError, match="names no series"):
        parse_rules({"rules": [{"type": "threshold", "name": "r"}]})
    with pytest.raises(ValueError, match="severity"):
        parse_rules({"rules": [{"type": "threshold", "name": "r",
                                "series": "edl_x", "severity": "sev1"}]})


def test_default_rules_parse_and_series_exist():
    """Every series the shipped rules watch exists in the core
    catalog — the static analyzer pins the same property, this pins it
    at runtime against ensure_core_series."""
    rules = parse_rules(load_rules_doc())
    assert len(rules) == len(DEFAULT_RULES["rules"])
    reg = ensure_core_series(MetricsRegistry())
    registered = {f["name"] for f in reg.snapshot()["families"]}
    for rule in rules:
        assert rule.series in registered, rule.name


def test_load_rules_doc_returns_deep_copy():
    doc = load_rules_doc()
    doc["rules"][0]["objective"] = 0.5
    assert DEFAULT_RULES["rules"][0]["objective"] != 0.5


def test_engine_rejects_bad_time_scale():
    with pytest.raises(ValueError):
        AlertEngine([], time_scale=0.0)


def test_to_block_is_jsonable(tmp_path):
    engine = AlertEngine(
        [ThresholdRule("hot", "edl_temp", op=">", value=5.0,
                       window_s=10.0)]
    )
    db = db_with_gauge(tmp_path, "edl_temp", [9.0] * 3)
    engine.evaluate(db, 1002.5)
    block = json.loads(json.dumps(engine.to_block()))
    assert block["fired_total"] == 1
    assert block["active"][0]["rule"] == "hot"
    assert block["last_transition"]["transition"] == "fire"


def test_broken_rule_does_not_blind_the_engine(tmp_path):
    class Exploding(alerts.Rule):
        def firing(self, db, now):
            raise RuntimeError("boom")

    engine = AlertEngine([
        Exploding("bad"),
        ThresholdRule("hot", "edl_temp", op=">", value=5.0,
                      window_s=10.0),
    ])
    db = db_with_gauge(tmp_path, "edl_temp", [9.0] * 3)
    trs = engine.evaluate(db, 1002.5)
    assert [t["rule"] for t in trs] == ["hot"]


# ---------------------------------------------------------------------------
# postmortem integration


def rec_events(*emits):
    rec = FlightRecorder()
    for kind, site, sev in emits:
        rec.emit(kind, severity=sev, site=site)
    return rec.records()


def test_alert_chains_open_incident_is_a_problem():
    evs = rec_events(("alert.fire", "alert.gp_fast", "error"))
    chains = postmortem.alert_chains(evs)
    assert len(chains) == 1 and not chains[0]["ok"]
    assert "never resolved" in chains[0]["problems"][0]

    evs = rec_events(
        ("alert.fire", "alert.gp_fast", "error"),
        ("alert.resolve", "alert.gp_fast", "info"),
    )
    chains = postmortem.alert_chains(evs)
    assert len(chains) == 1 and chains[0]["ok"]


def test_verify_recovered_over_alert_sites():
    complete = rec_events(
        ("alert.fire", "alert.gp_fast", "error"),
        ("alert.resolve", "alert.gp_fast", "info"),
    )
    assert postmortem.verify_recovered(complete, "alert.") == []

    open_incident = rec_events(("alert.fire", "alert.gp_fast", "error"))
    problems = postmortem.verify_recovered(open_incident, "alert.")
    assert any("never resolved" in p for p in problems)

    # a lane that produced neither faults nor alerts asserts nothing
    problems = postmortem.verify_recovered([], "alert.")
    assert problems and "no injected faults or fired alerts" in problems[0]


# ---------------------------------------------------------------------------
# monitor surface


def test_monitor_sample_carries_alerts_block(tmp_path):
    from edl_tpu.monitor.collector import Collector, MonitorSample

    engine = AlertEngine(
        [ThresholdRule("hot", "edl_temp", op=">", value=5.0,
                       window_s=10.0, severity="page")]
    )
    db = db_with_gauge(tmp_path, "edl_temp", [9.0] * 3)

    class _Src:
        def sample(self):
            return MonitorSample(ts=1002.5)

    def alerts_source():
        engine.evaluate(db, 1002.5)
        return engine.to_block()

    c = Collector(_Src(), alerts_source=alerts_source)
    s = c.poll()
    assert s.alerts["active"][0]["rule"] == "hot"
    rec = s.to_record()
    assert rec["alerts"]["fired_total"] == 1
    assert "ALERTS: hot[page]" in s.render()
