"""ElasticDataQueue: lease/ack, timeout redelivery, membership release,
multi-pass (reference semantics: master task queue,
docker/paddle_k8s:28-31 -chunk-per-task=1 -task-timout-dur=16s)."""

import time

from edl_tpu.runtime.data import ElasticDataQueue


def test_lease_ack_drains():
    q = ElasticDataQueue(n_samples=100, chunk_size=10, passes=1)
    seen = []
    while True:
        t = q.get_task("w0")
        if t is None:
            break
        seen.append((t.start, t.end))
        q.ack(t.task_id)
    assert len(seen) == 10
    assert q.done()
    # full coverage, no overlap
    covered = sorted(seen)
    assert covered[0] == (0, 10) and covered[-1] == (90, 100)


def test_release_worker_redelivers():
    q = ElasticDataQueue(n_samples=30, chunk_size=10, passes=1)
    t0 = q.get_task("w0")
    t1 = q.get_task("w1")
    assert t0 and t1
    n = q.release_worker("w0")  # w0 dies mid-chunk
    assert n == 1
    # w1 finishes everything, including the redelivered chunk
    q.ack(t1.task_id)
    got = []
    while (t := q.get_task("w1")) is not None:
        got.append(t.start)
        q.ack(t.task_id)
    assert t0.start in got
    assert q.done()


def test_lease_timeout_redelivers():
    q = ElasticDataQueue(n_samples=20, chunk_size=10, passes=1, lease_timeout_s=0.05)
    t0 = q.get_task("w0")
    t1 = q.get_task("w0")
    assert q.get_task("w0") is None  # all leased
    time.sleep(0.08)  # both leases expire
    t0b = q.get_task("w1")
    assert t0b is not None and t0b.failures == 1
    assert not q.done()


def test_passes_replay():
    q = ElasticDataQueue(n_samples=20, chunk_size=10, passes=3)
    count = 0
    while (t := q.get_task("w")) is not None:
        count += 1
        q.ack(t.task_id)
    assert count == 6  # 2 chunks x 3 passes
    assert q.done()


def test_queue_batcher_full_coverage_with_misaligned_sizes():
    # chunk 64, batch 48: every sample must be delivered exactly once and
    # tasks acked only when fully consumed.
    import numpy as np

    from edl_tpu.runtime.data import QueueBatcher

    q = ElasticDataQueue(n_samples=320, chunk_size=64, passes=1)
    data = np.arange(320)
    b = QueueBatcher(q, lambda t: {"i": data[t.start : t.end]})
    seen = []
    while (batch := b.next_batch(48)) is not None:
        seen.extend(batch["i"].tolist())
    assert sorted(seen) == list(range(320))  # exact coverage, no drops
    assert q.done()


def test_poison_task_dies_after_max_failures():
    q = ElasticDataQueue(n_samples=10, chunk_size=10, passes=1, lease_timeout_s=0.01)
    for _ in range(10):  # lease, let it expire, repeat past MAX_TASK_FAILURES
        t = q.get_task("w")
        if t is None:
            break
        time.sleep(0.02)
    assert q.progress()["dead"] == 1
    assert q.done() or q.progress()["todo"] == 0


def test_static_shard_reader_partition():
    """Chunk i belongs to worker i % N: shards are disjoint and cover
    every sample exactly once (the cluster_reader contract, reference:
    example/fit_a_line/fluid/common.py:24-40)."""
    from edl_tpu.runtime.data import StaticShardReader

    n, chunk, workers = 1000, 64, 3  # ragged final chunk
    shards = [
        StaticShardReader(n, chunk, workers, w).epoch_indices()
        for w in range(workers)
    ]
    flat = sorted(i for s in shards for i in s)
    assert flat == list(range(n))
    # deterministic round-robin chunk ownership
    r0 = StaticShardReader(n, chunk, workers, 0)
    assert [t.task_id for t in r0.chunks()] == [0, 3, 6, 9, 12, 15]


def test_static_shard_reader_validates():
    import pytest as _pytest

    from edl_tpu.runtime.data import StaticShardReader

    with _pytest.raises(ValueError):
        StaticShardReader(10, 2, 2, 2)
    with _pytest.raises(ValueError):
        StaticShardReader(0, 2, 2, 0)


# -- file-backed shards (runtime/shards.py) ---------------------------------


def test_write_shards_and_range_fetch(tmp_path):
    """Roundtrip: rows written as shard files come back exactly, for
    ranges inside one file and spanning file boundaries."""
    import numpy as np

    from edl_tpu.runtime.shards import FileShardSource, write_shards

    rng = np.random.RandomState(0)
    rows = {
        "x": rng.randn(1000, 4).astype(np.float32),
        "label": rng.randint(0, 2, (1000, 1)).astype(np.float32),
    }
    m = write_shards(str(tmp_path / "ds"), rows, shard_size=256)
    assert m["n_samples"] == 1000 and len(m["files"]) == 4

    src = FileShardSource(str(tmp_path / "ds"))
    assert src.n_samples == 1000
    got = src.fetch_range(100, 140)  # inside shard 0
    np.testing.assert_array_equal(got["x"], rows["x"][100:140])
    got = src.fetch_range(200, 600)  # spans three files
    np.testing.assert_array_equal(got["x"], rows["x"][200:600])
    np.testing.assert_array_equal(got["label"], rows["label"][200:600])
    got = src.fetch_range(900, 1000)  # ragged final shard
    np.testing.assert_array_equal(got["x"], rows["x"][900:])

    import pytest as _pytest

    with _pytest.raises(IndexError):
        src.fetch_range(990, 1010)
    with _pytest.raises(FileNotFoundError):
        FileShardSource(str(tmp_path / "nope"))


def test_real_files_through_lease_queue(tmp_path):
    """The VERDICT r1 #4 done-criterion: rows from REAL on-disk shard
    files flow through the elastic lease queue with exactly-once
    coverage per pass — two competing workers, every sample delivered
    once, values bit-identical to the files."""
    import numpy as np

    from edl_tpu.runtime.data import ElasticDataQueue, QueueBatcher
    from edl_tpu.runtime.shards import FileShardSource, write_shards

    rng = np.random.RandomState(1)
    rows = {"x": rng.randn(640, 3).astype(np.float32)}
    # x[:, 0] carries the sample's own index so delivery is auditable
    rows["x"][:, 0] = np.arange(640)
    write_shards(str(tmp_path / "ds"), rows, shard_size=100)  # ragged

    src = FileShardSource(str(tmp_path / "ds"))
    q = ElasticDataQueue(src.n_samples, chunk_size=96, passes=1)
    batchers = [QueueBatcher(q, src.fetch, worker=f"w{i}") for i in range(2)]

    delivered = []
    done = 0
    while done < 2:
        done = 0
        for b in batchers:
            batch = b.next_batch(64)
            if batch is None:
                done += 1
            else:
                delivered.append(batch["x"])
    ids = np.concatenate([d[:, 0] for d in delivered])
    assert sorted(ids.astype(int).tolist()) == list(range(640))
    assert q.done()


def test_queue_batcher_rollover_spans_passes(tmp_path):
    """rollover=True tops a pass-boundary short batch up from the next
    pass; without it the boundary batch is short."""
    import numpy as np

    from edl_tpu.runtime.data import ElasticDataQueue, QueueBatcher

    def fetch(task):
        return {"i": np.arange(task.start, task.end, dtype=np.int64)}

    q = ElasticDataQueue(n_samples=10, chunk_size=5, passes=3)
    b = QueueBatcher(q, fetch)
    first = b.next_batch(8, rollover=True)
    assert first["i"].shape[0] == 8
    boundary = b.next_batch(8, rollover=True)  # 2 left in pass 0 + 6 of pass 1
    assert boundary["i"].shape[0] == 8
    assert boundary["i"][:2].tolist() == [8, 9]
    assert boundary["i"][2:4].tolist() == [0, 1]
    # drain to the true end: final batch may be short, then None
    total = first["i"].shape[0] + boundary["i"].shape[0]
    while True:
        nxt = b.next_batch(8, rollover=True)
        if nxt is None:
            break
        total += nxt["i"].shape[0]
    assert total == 30  # 3 passes x 10 samples, exactly
