"""Crash-safe serving: the engine rebuilds from host truth after any
exception escaping dispatch/prefill/drain, and the replay is
token-identical under greedy decoding.

The contract (ISSUE 4): slots retain ``prompt``; on a fault the engine
discards in-flight blocks, reallocates the KV cache + device slot
state, and re-prefills each live slot from ``prompt + generated`` —
greedy argmax over the full context emits exactly the token the lost
decode step would have. Recovery is bounded per request
(``max_recoveries``), overdue work is shed (deadlines), and every
recovery is counted. Faults are injected deterministically through
``edl_tpu.utils.faults`` at the engine's REAL fault points.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.serving.engine import ContinuousBatchingEngine
from edl_tpu.utils import faults

CFG = llama.LlamaConfig.tiny()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _sequential(prompt, max_new):
    toks = llama.generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CFG, max_new=max_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


PROMPTS = [list(range(2, 2 + n)) for n in (4, 7, 3, 9, 5, 6)]
MAX_NEWS = [6, 3, 13, 5, 7, 9]


def _run_mixed(horizon=4, max_recoveries=2, plan=None, seed=0,
               **engine_kw):
    """The mid-stream workload: 3 requests in, one block dispatched,
    3 more join — so a crash lands with requests at different depths."""
    if plan:
        faults.arm(plan, seed=seed)
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=3, max_len=64, horizon=horizon,
        max_recoveries=max_recoveries, **engine_kw,
    )
    for i in range(3):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    eng.step()  # first block in flight
    for i in range(3, 6):
        eng.submit(f"r{i}", PROMPTS[i], MAX_NEWS[i])
    res = eng.run()
    faults.disarm()
    return eng, res


def test_paged_dispatch_fault_token_identity():
    """The recovery contract holds with the PAGED cache: a crash
    mid-dispatch discards the block pool, and ``_recover`` rebuilds
    allocator, tables, and prefix cache from host truth before the
    re-prefill — greedy tokens stay identical and no pool blocks leak
    (the deep paged recovery matrix lives in tests/test_paged_kv.py)."""
    eng, res = _run_mixed(
        plan="serve.dispatch:raise@n=3",
        block_size=8, prefix_cache=True,
    )
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} diverged after paged crash recovery"
        )
        assert res[f"r{i}"].outcome in ("done", "eos")
    assert eng.recoveries >= 1
    # every allocated block is accounted for by the prefix cache —
    # finished slots returned theirs to the pool
    assert eng._balloc.allocated_blocks == len(eng._prefix)


def test_dispatch_fault_token_identity():
    """The acceptance contract: with ``serve.dispatch:raise@n=3`` armed
    the greedy output of EVERY request — including those mid-stream at
    the crash — is token-identical to the fault-free run, and the
    recovery count respects the bound."""
    eng, res = _run_mixed(plan="serve.dispatch:raise@n=3")
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} diverged after crash recovery"
        )
        assert res[f"r{i}"].outcome in ("done", "eos")
    assert 1 <= eng.recoveries <= eng.max_recoveries
    assert all(
        (sl is None or sl.recoveries <= eng.max_recoveries)
        for sl in eng._slots
    )
    snap = eng.metrics.snapshot()
    assert snap["recoveries"] == eng.recoveries
    assert snap["completed"] == 6


@pytest.mark.parametrize("plan", [
    "serve.drain:raise@n=2",        # a device-complete block is lost
    "serve.prefill:raise@n=2",      # crash mid-admission: requeue at head
    "serve.dispatch:raise@n=2;serve.drain:raise@n=5",  # combined
])
def test_fault_sites_token_identity(plan):
    eng, res = _run_mixed(horizon=8, max_recoveries=3, plan=plan)
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(PROMPTS[i], MAX_NEWS[i]), (
            f"r{i} under {plan}"
        )
    assert eng.recoveries >= 1


def test_recovery_at_every_horizon():
    """The replay contract holds at H=1 (per-token) and deep horizons
    alike — the lost-block size changes, the output must not."""
    for h in (1, 4, 16):
        _, res = _run_mixed(horizon=h, plan="serve.dispatch:raise@n=2")
        for i in range(6):
            assert res[f"r{i}"].tokens == _sequential(
                PROMPTS[i], MAX_NEWS[i]
            ), f"r{i} at horizon {h}"


def test_bounded_recovery_failed_outcome_and_engine_survives():
    """A poisoned path (every dispatch faults) cannot wedge the engine:
    each request burns its ``max_recoveries`` and finishes "failed";
    once the fault clears, the SAME engine serves new work correctly."""
    faults.arm("serve.dispatch:raise@every=1", seed=0)
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=64, max_recoveries=2
    )
    eng.submit("doomed", [1, 2, 3], 8)
    res = eng.run()
    faults.disarm()
    assert res["doomed"].outcome == "failed"
    # partial progress was preserved: each recovery replays one token
    assert 0 < len(res["doomed"].tokens) < 8
    assert eng.recoveries == eng.max_recoveries + 1
    assert eng.metrics.outcomes["failed"] == 1
    # the engine object is still healthy post-chaos
    eng.submit("fresh", [4, 5, 6], 5)
    res = eng.run()
    assert res["fresh"].tokens == _sequential([4, 5, 6], 5)
    assert res["fresh"].outcome == "done"


def test_prefill_fault_preserves_fifo_and_request():
    """A crash mid-admission requeues the popped request at the queue
    HEAD: nothing is lost and it still completes token-identically."""
    faults.arm("serve.prefill:raise@n=1", seed=0)
    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=1, max_len=64)
    eng.submit("first", [1, 2, 3, 4], 5)
    eng.submit("second", [5, 6, 7], 4)
    res = eng.run()
    faults.disarm()
    assert res["first"].tokens == _sequential([1, 2, 3, 4], 5)
    assert res["second"].tokens == _sequential([5, 6, 7], 4)
    # FIFO survived the crash: "first" finished before "second" started
    m = eng.metrics.requests
    assert m["first"].finish_s <= m["second"].admit_s


def test_recovery_counter_in_registry():
    from edl_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.reset_default_registry()
    _run_mixed(plan="serve.dispatch:raise@n=2")
    c = reg.get("edl_serving_recoveries_total")
    assert c is not None and c.value() >= 1
    f = reg.get("edl_faults_injected_total")
    assert f is not None and f.value(site="serve.dispatch") >= 1


# -- deadlines + load shedding ----------------------------------------------


def test_slot_deadline_eviction_timeout_outcome():
    """A live slot past its deadline is evicted between blocks with
    outcome "timeout" and its partial tokens; slot-mates continue."""
    t = [0.0]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=64, clock=lambda: t[0]
    )
    eng.submit("slow", [1, 2, 3], 40, deadline_s=5.0)
    eng.submit("ok", [4, 5, 6], 4)
    for _ in range(3):
        eng.step()
    t[0] = 10.0  # past slow's deadline
    res = eng.run()
    assert res["slow"].outcome == "timeout"
    assert 0 < len(res["slow"].tokens) < 40
    # the partial prefix matches the fault-free stream (nothing bogus)
    full = _sequential([1, 2, 3], 40)
    assert res["slow"].tokens == full[: len(res["slow"].tokens)]
    assert res["ok"].tokens == _sequential([4, 5, 6], 4)
    assert eng.metrics.outcomes["timeout"] == 1


def test_queue_deadline_shedding():
    """A queued request whose deadline lapses while waiting is shed
    (``rejected:timeout``) without ever touching the device."""
    t = [0.0]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=1, max_len=64, clock=lambda: t[0]
    )
    eng.submit("hog", [1, 2, 3], 12)
    eng.submit("stale", [4, 5], 4, deadline_s=1.0)
    eng.step()  # hog admitted; stale waits
    t[0] = 2.0  # stale's deadline passes in the queue
    res = eng.run()
    assert res["hog"].tokens == _sequential([1, 2, 3], 12)
    assert res["stale"].outcome == "timeout"
    assert res["stale"].tokens == []
    assert eng.metrics.rejected["timeout"] == 1
    snap = eng.metrics.snapshot()
    assert snap["rejected_timeout"] == 1
    # shed before prefill: exactly one admission happened (the hog)
    assert snap["dispatches_prefill"] == 1


def test_submit_rejects_nonpositive_deadline():
    from edl_tpu.serving.scheduler import AdmissionError

    eng = ContinuousBatchingEngine(PARAMS, CFG, max_slots=1, max_len=32)
    with pytest.raises(AdmissionError) as e:
        eng.submit("bad", [1, 2], 3, deadline_s=0.0)
    assert e.value.reason == "bad_request"


# -- run(max_steps) drains in-flight blocks (satellite) ----------------------


def test_run_max_steps_drains_inflight():
    """run(max_steps) used to return with dispatched-but-undrained
    blocks, silently missing tokens the device already produced; it
    must drain before returning."""
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=1, max_len=64, horizon=8
    )
    eng.submit("a", [1, 2, 3], 5)  # finishes inside the first block
    res = eng.run(max_steps=1)  # step 1 admits + dispatches, no drain yet
    assert not eng._inflight
    assert res["a"].tokens == _sequential([1, 2, 3], 5)
    assert res["a"].outcome == "done"


# -- crash recovery mid-speculation (ISSUE 14) -------------------------------

# repetitive prompts so the n-gram drafter fires and the crash lands
# while verify dispatches are actually speculating
SPEC_PROMPTS = [[1, 2, 3, 4, 1, 2, 3, 4, 1, 2], [3] * 8, [9, 10, 11],
                [2, 5, 2, 5, 2, 5], [6, 7, 8, 9], [4] * 6]
SPEC_MAX_NEWS = [17, 11, 5, 13, 7, 9]


def _run_spec_mixed(plan, **engine_kw):
    faults.arm(plan, seed=0)
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=3, max_len=96, horizon=1,
        max_recoveries=3, spec_k=4, spec_ngram=3, **engine_kw,
    )
    for i in range(3):
        eng.submit(f"r{i}", SPEC_PROMPTS[i], SPEC_MAX_NEWS[i])
    eng.step()
    for i in range(3, 6):
        eng.submit(f"r{i}", SPEC_PROMPTS[i], SPEC_MAX_NEWS[i])
    res = eng.run()
    faults.disarm()
    return eng, res


@pytest.mark.parametrize("plan", [
    "serve.dispatch:raise@n=3",   # a verify dispatch is lost
    "serve.drain:raise@n=4",      # a device-complete verify block lost
])
def test_spec_dispatch_fault_token_identity(plan):
    """The recovery contract holds MID-SPECULATION: a crash while
    verify blocks are in flight replays every live slot from its
    committed ``prompt + generated`` — accepted-but-undrained tokens
    exist only on device and are regenerated, so every stream stays
    token-identical to sequential ``generate``."""
    eng, res = _run_spec_mixed(plan)
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(
            SPEC_PROMPTS[i], SPEC_MAX_NEWS[i]
        ), f"r{i} diverged after crash mid-speculation under {plan}"
        assert res[f"r{i}"].outcome in ("done", "eos")
    assert eng.recoveries >= 1
    # the workload really speculated: verify dispatches ran and
    # drafts were accepted despite the crash
    snap = eng.metrics.snapshot()
    assert snap["dispatches_verify"] >= 1
    assert snap["spec_accepted"] >= 1


def test_spec_paged_dispatch_fault_token_identity():
    """Paged twin: recovery rebuilds pool/tables/prefix-cache while
    verify blocks route through block tables — identity holds and no
    pool blocks leak."""
    eng, res = _run_spec_mixed(
        "serve.dispatch:raise@n=3", block_size=8, prefix_cache=True,
    )
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(
            SPEC_PROMPTS[i], SPEC_MAX_NEWS[i]
        ), f"r{i} diverged after paged crash mid-speculation"
    assert eng.recoveries >= 1
    assert eng.metrics.snapshot()["dispatches_verify"] >= 1
    assert eng._balloc.allocated_blocks == len(eng._prefix)
