"""SLO-goodput load harness (serving/loadgen.py + obs/slo.py + the
ServingMetrics latency decomposition).

All jax-free: the generator, the goodput math, and the decomposition
are host bookkeeping driven by injectable clocks — a test failure
here is an accounting bug, never a device flake. The real-engine end
of the harness is CI-covered by `edl loadgen --dryrun` (run_tests.sh
phase 7) and the exp_serving scrape lane.
"""

import json
import math

import numpy as np
import pytest

from edl_tpu.obs import slo
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.serving import loadgen
from edl_tpu.serving.metrics import ServingMetrics
from edl_tpu.serving.scheduler import AdmissionError, Request, RequestQueue


def _metrics(t):
    """A ServingMetrics on a fake clock and a PRIVATE registry (no
    cross-test pollution through the process default)."""
    return ServingMetrics(clock=lambda: t[0], registry=MetricsRegistry())


# -- generator determinism ---------------------------------------------------


@pytest.mark.parametrize("arrival", ["poisson", "burst", "fixed"])
def test_same_seed_byte_identical(arrival):
    """The CI contract: same spec => byte-identical workload bytes;
    a different seed diverges."""
    spec = loadgen.WorkloadSpec(
        seed=7, n_requests=40, rate_rps=20.0, arrival=arrival
    )
    a = loadgen.workload_jsonl(loadgen.build(spec))
    b = loadgen.workload_jsonl(loadgen.build(spec))
    assert a == b
    other = loadgen.WorkloadSpec(
        seed=8, n_requests=40, rate_rps=20.0, arrival=arrival
    )
    assert loadgen.workload_jsonl(loadgen.build(other)) != a


def test_shared_prefix_zero_is_byte_identical_to_default():
    """The knob's off-position draws NOTHING from the rng stream —
    pre-knob workload bytes are preserved (the CI cmp gate in
    run_tests.sh phase 7 depends on it)."""
    base = loadgen.WorkloadSpec(seed=9, n_requests=32, rate_rps=16.0)
    off = loadgen.WorkloadSpec(
        seed=9, n_requests=32, rate_rps=16.0, shared_prefix_frac=0.0
    )
    assert (
        loadgen.workload_jsonl(loadgen.build(base))
        == loadgen.workload_jsonl(loadgen.build(off))
    )


def test_shared_prefix_injects_per_tenant_templates():
    """With the knob on, ~frac of each tenant's requests start with
    ONE fixed template (drawn once per tenant), the rest stay fully
    random — and the workload is still seed-deterministic and inside
    the tenant prompt bounds."""
    spec = loadgen.WorkloadSpec(
        seed=9, n_requests=200, rate_rps=16.0,
        shared_prefix_frac=0.6, shared_prefix_len=6,
    )
    reqs = loadgen.build(spec)
    assert loadgen.workload_jsonl(loadgen.build(spec)) == (
        loadgen.workload_jsonl(reqs)
    )
    shared = total = 0
    by_tenant = {}
    for r in reqs:
        if len(r.prompt) > spec.shared_prefix_len + 1:
            by_tenant.setdefault(r.tenant, []).append(
                tuple(r.prompt[: spec.shared_prefix_len])
            )
    for prefixes in by_tenant.values():
        counts = {}
        for p in prefixes:
            counts[p] = counts.get(p, 0) + 1
        shared += max(counts.values())  # the template's share
        total += len(prefixes)
    assert 0.4 <= shared / total <= 0.8, (shared, total)
    tenants = {t.name: t for t in spec.tenants}
    for r in reqs:
        assert 1 <= len(r.prompt) <= tenants[r.tenant].prompt_max
        assert all(0 <= tok < spec.vocab for tok in r.prompt)


def test_shared_prefix_validation():
    with pytest.raises(ValueError):
        loadgen.build(loadgen.WorkloadSpec(shared_prefix_frac=1.5))
    with pytest.raises(ValueError):
        loadgen.build(
            loadgen.WorkloadSpec(
                shared_prefix_frac=0.5, shared_prefix_len=0
            )
        )


def test_repetition_zero_is_byte_identical_to_default():
    """Same off-position contract as shared_prefix: repetition_frac=0
    draws nothing extra, so pre-knob workload bytes are preserved (the
    CI cmp gate)."""
    base = loadgen.WorkloadSpec(seed=9, n_requests=32, rate_rps=16.0)
    off = loadgen.WorkloadSpec(
        seed=9, n_requests=32, rate_rps=16.0, repetition_frac=0.0
    )
    assert (
        loadgen.workload_jsonl(loadgen.build(base))
        == loadgen.workload_jsonl(loadgen.build(off))
    )


def test_repetition_tiles_prompts():
    """With the knob on, ~frac of the prompts become a short pattern
    tiled to the drawn length — the traffic shape the n-gram drafter
    can predict — and the build stays seed-deterministic."""
    spec = loadgen.WorkloadSpec(
        seed=9, n_requests=200, rate_rps=16.0,
        repetition_frac=0.5, repetition_len=4,
    )
    reqs = loadgen.build(spec)
    assert loadgen.workload_jsonl(loadgen.build(spec)) == (
        loadgen.workload_jsonl(reqs)
    )

    def is_tiled(p, period):
        return len(p) > period and all(
            p[i] == p[i % period] for i in range(len(p))
        )

    tiled = sum(1 for r in reqs if is_tiled(r.prompt, 4))
    eligible = sum(1 for r in reqs if len(r.prompt) > 4)
    assert 0.3 <= tiled / eligible <= 0.7, (tiled, eligible)
    # prompt lengths and vocab bounds are untouched by the rewrite
    for r in reqs:
        assert all(0 <= t < spec.vocab for t in r.prompt)


def test_repetition_validation():
    with pytest.raises(ValueError):
        loadgen.build(loadgen.WorkloadSpec(repetition_frac=-0.1))
    with pytest.raises(ValueError):
        loadgen.build(
            loadgen.WorkloadSpec(repetition_frac=0.5, repetition_len=0)
        )


def test_workload_shape_and_bounds():
    spec = loadgen.WorkloadSpec(seed=0, n_requests=64, rate_rps=16.0)
    reqs = loadgen.build(spec)
    assert len(reqs) == 64
    cmap = spec.class_map()
    tenants = {t.name: t for t in spec.tenants}
    arrive = [r.arrive_s for r in reqs]
    assert arrive == sorted(arrive) and arrive[0] >= 0.0
    for r in reqs:
        t = tenants[r.tenant]
        assert 1 <= len(r.prompt) <= t.prompt_max
        assert 1 <= r.max_new <= t.output_max
        assert all(0 <= tok < spec.vocab for tok in r.prompt)
        # the SLO contract is stamped onto the request itself
        assert r.slo_class == t.slo_class
        assert r.ttft_slo_s == cmap[t.slo_class].ttft_slo_s
    # every line parses back and carries the labels
    for line in loadgen.workload_jsonl(reqs).splitlines():
        rec = json.loads(line)
        assert rec["tenant"] in tenants and rec["slo_class"] in cmap


def test_fixed_arrivals_are_evenly_spaced():
    spec = loadgen.WorkloadSpec(
        seed=3, n_requests=10, rate_rps=4.0, arrival="fixed"
    )
    reqs = loadgen.build(spec)
    gaps = [
        round(b.arrive_s - a.arrive_s, 6)
        for a, b in zip(reqs, reqs[1:])
    ]
    assert gaps == [pytest.approx(0.25)] * 9


def test_burst_mean_rate_is_preserved():
    """The MMPP redistributes arrivals into bursts but must not change
    the long-run offered load."""
    spec = loadgen.WorkloadSpec(
        seed=1, n_requests=4000, rate_rps=50.0, arrival="burst",
        burst_factor=6.0, burst_dwell_s=0.5,
    )
    reqs = loadgen.build(spec)
    span = reqs[-1].arrive_s
    rate = len(reqs) / span
    assert rate == pytest.approx(50.0, rel=0.15)
    # and it actually bursts: inter-arrival variance well above the
    # exponential's (CV > 1 is the definition of bursty)
    gaps = np.diff([r.arrive_s for r in reqs])
    cv = float(np.std(gaps) / np.mean(gaps))
    assert cv > 1.1, f"burst arrivals look Poisson (cv={cv:.2f})"


def test_bad_specs_raise():
    with pytest.raises(ValueError):
        loadgen.build(loadgen.WorkloadSpec(rate_rps=0.0))
    with pytest.raises(ValueError):
        loadgen.build(loadgen.WorkloadSpec(arrival="nope"))
    with pytest.raises(ValueError):
        loadgen.build(
            loadgen.WorkloadSpec(
                tenants=(loadgen.TenantSpec("x", slo_class="missing"),)
            )
        )


def test_step_indexed_workload_matches_legacy_draws():
    """The soak/bench builder moved here verbatim: same RandomState,
    same draw order, same bytes as the pre-refactor exp_serving code
    (the dispatch-bound CI assertions were tuned on these)."""
    rng1 = np.random.RandomState(5)
    got = loadgen.step_indexed_workload(
        6, 512, rng1, prompt_range=(3, 8), max_new_range=(64, 80)
    )
    rng2 = np.random.RandomState(5)
    step = 0
    for i, g in enumerate(got):
        t0 = int(rng2.randint(3, 8))
        max_new = int(rng2.randint(64, 80))
        prompt = rng2.randint(0, 512, t0).tolist()
        assert g == {"rid": f"r{i}", "prompt": prompt,
                     "max_new": max_new, "arrive": step}
        step += int(rng2.randint(0, 4))


# -- wall-clock replay (fake engine, fake clock) -----------------------------


class _FakeEngine:
    """Minimal engine double: admission-bounded queue, fixed per-step
    service, the same submit/step/has_work surface replay() drives."""

    def __init__(self, clock, depth=4, steps_per_req=2):
        self.clock = clock
        self.queue = RequestQueue(max_total_len=64, max_depth=depth,
                                  clock=clock)
        self.steps_per_req = steps_per_req
        self.submits = []
        self.served = []
        self._work = 0

    @property
    def has_work(self):
        return self.queue.depth > 0 or self._work > 0

    def submit(self, rid, prompt, max_new, tenant=None, slo_class=None):
        self.submits.append((rid, self.clock()))
        self.queue.submit(Request(rid=rid, prompt=list(prompt),
                                  max_new=max_new, tenant=tenant,
                                  slo_class=slo_class))

    def step(self):
        if self._work == 0 and self.queue.depth:
            self.queue.pop()
            self._work = self.steps_per_req
        if self._work:
            self._work -= 1
            if self._work == 0:
                self.served.append(self.clock())


def test_replay_paces_submissions_on_the_wall_clock():
    t = [0.0]

    def clock():
        return t[0]

    def sleep(dt):
        t[0] += max(dt, 1e-4)

    fake = _FakeEngine(clock)
    orig_step = fake.step

    def step():
        t[0] += 0.01  # each engine step costs 10 ms of fake wall time
        orig_step()

    fake.step = step
    spec = loadgen.WorkloadSpec(
        seed=2, n_requests=8, rate_rps=5.0, arrival="fixed"
    )
    reqs = loadgen.build(spec)
    res = loadgen.replay(fake, reqs, clock=clock, sleep=sleep)
    assert res["submitted"] == 8 and res["rejected"] == 0
    assert len(fake.served) == 8
    # nothing submitted before its arrival offset
    by_rid = {r.rid: r.arrive_s for r in reqs}
    for rid, at in fake.submits:
        assert at >= by_rid[rid] - 1e-9
    assert res["wall_s"] >= reqs[-1].arrive_s


def test_replay_counts_shed_load_instead_of_dying():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    sleep = lambda dt: t.__setitem__(0, t[0] + max(dt, 1e-4))  # noqa: E731
    fake = _FakeEngine(clock, depth=1, steps_per_req=50)
    orig_step = fake.step

    def step():
        t[0] += 0.01
        orig_step()

    fake.step = step
    spec = loadgen.WorkloadSpec(
        seed=2, n_requests=12, rate_rps=200.0, arrival="poisson"
    )
    res = loadgen.replay(fake, loadgen.build(spec), clock=clock, sleep=sleep)
    assert res["rejected"] > 0  # queue_full landed as data, not a crash
    assert res["submitted"] + res["rejected"] == 12


# -- the latency decomposition invariant -------------------------------------


def test_decomposition_phases_sum_exactly():
    """queue_wait + prefill + decode == finish - submit, per request,
    on a fake clock (the stamps are exactly adjacent by construction —
    any drift means a phase got double-charged)."""
    t = [0.0]
    m = _metrics(t)
    walk = {
        "a": (0.0, 0.5, 0.9, 4.0),  # submit, pop, first, finish
        "b": (0.1, 2.0, 2.25, 6.5),
    }
    for rid, (s, p, f1, fin) in walk.items():
        t[0] = s
        m.on_submit(rid)
        t[0] = p
        m.on_pop(rid)
        t[0] = f1
        m.on_admit(rid, 4)
        m.on_token(rid)
        t[0] = fin
        m.on_tokens(rid, 3)
        m.on_finish(rid, "done")
    for rid, (s, p, f1, fin) in walk.items():
        ph = m.phase_breakdown(rid)
        assert ph["queue_wait_s"] == pytest.approx(p - s)
        assert ph["prefill_s"] == pytest.approx(f1 - p)
        assert ph["decode_s"] == pytest.approx(fin - f1)
        assert ph["total_s"] == pytest.approx(fin - s)
        assert (
            ph["queue_wait_s"] + ph["prefill_s"] + ph["decode_s"]
            == pytest.approx(ph["total_s"])
        )
    # and the registry histograms observed the same phases
    snap = m.snapshot()
    assert snap["queue_wait_p99_s"] > 0.0
    assert snap["prefill_p99_s"] > 0.0


def test_honest_tail_itl_and_tpot():
    """A drained block lands as ONE full-gap observation + n-1 zeros:
    p99 ITL sees the stall the user saw (the old per-token mean hid a
    gap G as n observations of G/n). TPOT is the per-request
    amortization-proof figure: (finish - first) / (tokens - 1)."""
    t = [0.0]
    m = _metrics(t)
    m.on_submit("a")
    t[0] = 1.0
    m.on_pop("a")
    m.on_admit("a", 4)
    m.on_token("a")  # first token at t=1
    t[0] = 9.0
    m.on_tokens("a", 8)  # one block drained after an 8 s stall
    m.on_finish("a", "done")
    # count and sum stay exact (9 ITL observations? no: 1 gap + 7 zeros
    # from this drain = 8 observations, sum 8.0 — same as the old mean
    # bucketing), but the tail now holds the REAL 8 s gap
    st = m.itl_hist.stats()
    assert st["count"] == 8 and st["sum"] == pytest.approx(8.0)
    assert m.itl_hist.percentile(0.99) > 5.0  # the stall is visible
    # zeros land in the first bucket; interpolation keeps p50 sub-ms
    assert m.itl_hist.percentile(0.50) < 0.001
    # TPOT = (9 - 1) / (9 tokens - 1) = 1.0 s/token, exact in the
    # histogram sum; the snapshot percentile is the bucketed estimate
    st = m.tpot_hist.stats()
    assert st["count"] == 1 and st["sum"] == pytest.approx(1.0)
    assert 0.5 <= m.snapshot()["tpot_p50_s"] <= 1.0


def test_first_drain_zeros_unchanged():
    """Tokens beyond the first inside the SAME first drain still record
    0.0 ITL (they arrived together) — only later drains carry gaps."""
    t = [1.0]
    m = _metrics(t)
    m.on_submit("a")
    m.on_pop("a")
    m.on_tokens("a", 5)
    st = m.itl_hist.stats()
    assert st["count"] == 4 and st["sum"] == 0.0


# -- label propagation -------------------------------------------------------


def test_labels_propagate_to_snapshot_and_counters():
    t = [0.0]
    m = _metrics(t)
    m.on_submit("a", tenant="acme", slo_class="interactive")
    m.on_submit("b", tenant="batchco", slo_class="batch")
    m.on_submit("c", tenant="acme", slo_class="interactive")
    for rid in ("a", "b"):
        t[0] += 1.0
        m.on_pop(rid)
        m.on_token(rid)
        m.on_finish(rid, "done")
    m.on_reject("c", "queue_full")
    snap = m.snapshot()
    assert snap["class_interactive_finished"] == 2.0  # a done + c rejected
    assert snap["class_batch_finished"] == 1.0
    assert snap["tenant_acme_finished"] == 2.0
    assert snap["tenant_batchco_finished"] == 1.0
    # the labeled outcome counter (what a postmortem scrapes to answer
    # "which tenant got shed")
    c = m.registry.get("edl_serving_outcomes_total")
    assert c.value(outcome="done", tenant="acme",
                   slo_class="interactive") == 1.0
    assert c.value(outcome="rejected:queue_full", tenant="acme",
                   slo_class="interactive") == 1.0
    assert c.value(outcome="done", tenant="batchco",
                   slo_class="batch") == 1.0


def test_request_dataclass_carries_labels_through_queue():
    q = RequestQueue(max_total_len=32)
    q.submit(Request("r", [1, 2], 4, tenant="acme", slo_class="batch"))
    r = q.pop()
    assert r.tenant == "acme" and r.slo_class == "batch"
    # unlabeled requests stay None (the single-tenant feed)
    q.submit(Request("s", [1], 2))
    assert q.pop().tenant is None


# -- goodput math ------------------------------------------------------------


def _drive(m, t, rid, submit, pop, first, finish, tokens, outcome,
           tenant="t", slo_class="interactive"):
    t[0] = submit
    m.on_submit(rid, tenant=tenant, slo_class=slo_class)
    if pop is None:
        m.on_reject(rid, "timeout")
        return
    t[0] = pop
    m.on_pop(rid)
    t[0] = first
    m.on_admit(rid, 2)
    m.on_token(rid)
    if tokens > 1:
        t[0] = finish
        m.on_tokens(rid, tokens - 1)
    t[0] = finish
    m.on_finish(rid, outcome)


def test_goodput_hand_computed():
    """Three served + one shed request against hand-computed SLO
    attainment: interactive ttft<=1.0 tpot<=0.25."""
    t = [0.0]
    m = _metrics(t)
    classes = slo.classes_by_name(slo.default_classes(1.0, 0.25))
    # ttft 0.5 OK, tpot (4.5-0.5)/(21-1)=0.2 OK            -> good
    _drive(m, t, "good", 0.0, 0.2, 0.5, 4.5, 21, "done")
    # ttft 2.0 BAD (queue wait), tpot 0.1 OK               -> not good
    _drive(m, t, "late", 10.0, 11.8, 12.0, 13.0, 11, "done")
    # ttft 0.3 OK, tpot (28-20.3)/(12-1)=0.7 BAD           -> not good
    _drive(m, t, "slow", 20.0, 20.1, 20.3, 28.0, 12, "eos")
    # shed at pop                                          -> against
    _drive(m, t, "shed", 30.0, None, 0, 0, 0, "")
    report = slo.compute_goodput(
        slo.request_records(m), classes, wall_s=40.0
    )
    assert report["requests"] == 4
    assert report["served"] == 3 and report["good"] == 1
    assert report["shed"] == 1
    assert report["ttft_slo_attainment"] == pytest.approx(2 / 3)
    assert report["itl_slo_attainment"] == pytest.approx(2 / 3)
    assert report["goodput_rps"] == pytest.approx(1 / 40.0)
    assert report["throughput_rps"] == pytest.approx(3 / 40.0)
    assert report["goodput_fraction"] == pytest.approx(1 / 4)
    cc = report["classes"]["interactive"]
    assert cc["good"] == 1 and cc["shed"] == 1
    assert cc["ttft_slo_attainment"] == pytest.approx(2 / 3)
    tc = report["tenants"]["t"]
    assert tc["requests"] == 4 and tc["good"] == 1 and tc["shed"] == 1
    # the phase percentiles come from the served records exactly
    qw = report["phases"]["queue_wait_s"]
    assert qw["p50"] == pytest.approx(sorted([0.2, 1.8, 0.1])[1])


def test_goodput_timeout_and_unclassified():
    t = [0.0]
    m = _metrics(t)
    classes = slo.classes_by_name(slo.default_classes(1.0, 0.25))
    _drive(m, t, "to", 0.0, 0.1, 0.2, 3.0, 4, "timeout")
    _drive(m, t, "nolabel", 5.0, 5.1, 5.2, 6.0, 4, "done",
           tenant="", slo_class="")
    report = slo.compute_goodput(slo.request_records(m), classes, 10.0)
    assert report["timeout"] == 1
    # SLO-less feed: goodput degenerates to completion
    assert report["classes"]["unclassified"]["good"] == 1
    assert report["tenants"]["unattributed"]["requests"] == 1
    assert report["good"] == 1


def test_single_token_requests_pass_the_itl_leg():
    t = [0.0]
    m = _metrics(t)
    classes = slo.classes_by_name(slo.default_classes(1.0, 0.001))
    _drive(m, t, "one", 0.0, 0.1, 0.2, 0.2, 1, "done")
    report = slo.compute_goodput(slo.request_records(m), classes, 1.0)
    assert report["good"] == 1  # no TPOT exists for a 1-token answer


def test_percentiles_exact_order_stats():
    assert slo.percentiles([]) == {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    p = slo.percentiles(list(range(1, 101)), (0.5, 0.99))
    assert p["p50"] == pytest.approx(50.5)
    assert p["p99"] == pytest.approx(99.01)


def test_slo_gauges_update():
    t = [0.0]
    m = _metrics(t)
    classes = slo.classes_by_name(slo.default_classes(1.0, 0.25))
    _drive(m, t, "g", 0.0, 0.1, 0.2, 1.0, 5, "done")
    report = slo.compute_goodput(slo.request_records(m), classes, 2.0)
    reg = MetricsRegistry()
    slo.update_gauges(report, registry=reg)
    g = reg.get("edl_slo_ttft_ok_ratio")
    assert g.value(slo_class="interactive") == 1.0
    assert reg.get("edl_slo_goodput_rps").value() == pytest.approx(0.5)
    # render + json both digest the same report
    text = slo.render_report(report)
    assert "GOODPUT" in text and "CLASS interactive" in text
    json.dumps(report)  # JSON-able for `edl loadgen --json`


def test_report_survives_inf_slos():
    """Unknown classes get infinite deadlines — the report must stay
    JSON-renderable (inf never leaks into the output fields)."""
    t = [0.0]
    m = _metrics(t)
    _drive(m, t, "u", 0.0, 0.1, 0.2, 1.0, 5, "done",
           slo_class="mystery")
    report = slo.compute_goodput(slo.request_records(m), {}, 2.0)
    cc = report["classes"]["mystery"]
    assert cc["good"] == 1
    assert math.isinf(cc["ttft_slo_s"])  # explicit, not hidden


def test_admission_error_still_importable_from_loadgen():
    """replay() catches AdmissionError by identity — the import path
    must stay the scheduler's class."""
    assert loadgen.AdmissionError is AdmissionError
