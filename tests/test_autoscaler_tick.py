"""Autoscaler × FakeCluster integration: the elastic squeeze.

Miniature of the reference's demo trace (reference: doc/boss_tutorial.md
"Deploy Multiple Training Jobs": job example 10→3, example1 8→4,
example2 0→4 as contention rises): an elastic job grows to fill the
fleet, then gets squeezed down when a second job's pods pend.
"""

from edl_tpu.api.job import Event, TrainingJob
from edl_tpu.api.parser import JobParser
from edl_tpu.cluster.fake import FakeCluster, FakeHost
from edl_tpu.scheduler.autoscaler import Autoscaler


def make_job(name, lo, hi, chips=4):
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                "worker": {
                    "min_replicas": lo,
                    "max_replicas": hi,
                    "resources": {
                        "requests": {"cpu": "500m", "memory": "1Gi", "tpu": chips},
                        "limits": {"cpu": "500m", "memory": "1Gi", "tpu": chips},
                    },
                },
            },
        }
    )
    JobParser().validate(job)
    return job


def submit(cluster, asc, job):
    cluster.submit_job(job)
    cluster.create_worker_group(JobParser().parse_to_workers(job))
    asc._update_job_list(Event(Event.Type.ADD, job))


def test_grow_to_fill_then_squeeze():
    cluster = FakeCluster(
        hosts=[FakeHost(f"h{i}", 8000, 16000, 4) for i in range(4)]
    )
    asc = Autoscaler(cluster)

    j1 = make_job("alpha", lo=2, hi=8)
    submit(cluster, asc, j1)
    asc.tick()
    # 16 chips / 4 per worker: alpha grows to the whole fleet
    assert cluster.get_worker_group(j1).parallelism == 4
    assert cluster.job_pods(j1) == (4, 4, 0)

    j2 = make_job("beta", lo=2, hi=8)
    submit(cluster, asc, j2)
    # beta's pods pend (no chips free) → alpha is squeezed to make room
    asc.tick()
    assert cluster.get_worker_group(j1).parallelism == 2
    asc.tick()  # second tick: beta's pods are now placed
    assert cluster.job_pods(j2) == (2, 2, 0)
    r = cluster.inquiry_resource()
    assert r.chip_limit == 16  # fleet saturated, nothing pending

    # beta finishes → alpha grows back (elastic recovery)
    cluster.delete_worker_group("default", "beta-worker")
    cluster.delete_job("default", "beta")
    asc._update_job_list(Event(Event.Type.DEL, j2))
    asc.tick()
    assert cluster.get_worker_group(j1).parallelism == 4


def test_rescale_cooldown_damps_pingpong():
    # With a cooldown, a freshly-rescaled job is left alone next tick
    # (unless pods pend), so the fulfillment ping-pong cannot thrash.
    cluster = FakeCluster(
        hosts=[FakeHost(f"h{i}", 8000, 16000, 4) for i in range(4)]
    )
    asc = Autoscaler(cluster, rescale_cooldown_s=3600.0)
    j1 = make_job("alpha", lo=2, hi=8)
    submit(cluster, asc, j1)
    asc.tick()
    assert cluster.get_worker_group(j1).parallelism == 4
    p = cluster.get_worker_group(j1).parallelism
    for _ in range(3):
        asc.tick()
        assert cluster.get_worker_group(j1).parallelism == p
    # a pending job overrides the cooldown (reference semantics: pending
    # jobs may reschedule everything, pkg/autoscaler.go:487-511)
    j2 = make_job("beta", lo=2, hi=8)
    submit(cluster, asc, j2)
    asc.tick()
    assert cluster.get_worker_group(j1).parallelism == 2


def test_non_elastic_job_untouched():
    cluster = FakeCluster(hosts=[FakeHost("h0", 8000, 16000, 8)])
    asc = Autoscaler(cluster)
    j = make_job("fixed", lo=2, hi=2)
    submit(cluster, asc, j)
    asc.tick()
    assert cluster.get_worker_group(j).parallelism == 2
