"""Test harness: force an 8-device virtual CPU platform BEFORE jax import.

Stands in for a multi-chip TPU slice (SURVEY §4: multi-node testing
without a cluster). The driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
