"""Test harness: force an 8-device virtual CPU platform.

Stands in for a multi-chip TPU slice (SURVEY §4: multi-node testing
without a cluster). The driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip; bench.py alone uses the real chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from edl_tpu.utils.platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
