"""Test harness: force an 8-device virtual CPU platform.

Stands in for a multi-chip TPU slice (SURVEY §4: multi-node testing
without a cluster). The driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip; bench.py alone uses the real chip.

Note: the TPU plugin may already be registered by a sitecustomize at
interpreter start, so env vars alone are too late — jax.config wins.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
