"""Deploy & ops artifacts stay in sync with the code.

The reference ships TPR registration, a controller Deployment, and RBAC
(reference: k8s/thirdpartyresource.yaml, k8s/edl_controller.yaml,
k8s/rbac_admin.yaml) plus image builds (reference: Dockerfile,
docker/build.sh). These tests pin our analogs in deploy/ and docker/
to the TrainingJob dataclasses and the CLI so schema drift fails CI.
"""

import dataclasses
import pathlib
import subprocess
import sys

import pytest
import yaml

from edl_tpu.api.job import (
    JobPhase,
    MeshSpec,
    TrainingJobSpec,
    TrainingJobStatus,
    WorkerSpec,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_all(rel):
    return list(yaml.safe_load_all((REPO / rel).read_text()))


def _crd_v1_schema():
    (crd,) = _load_all("deploy/crd.yaml")
    (v1,) = [v for v in crd["spec"]["versions"] if v["name"] == "v1"]
    return crd, v1["schema"]["openAPIV3Schema"]


def test_crd_spec_covers_dataclass_fields():
    _, schema = _crd_v1_schema()
    spec_props = schema["properties"]["spec"]["properties"]
    declared = set(spec_props)
    actual = {f.name for f in dataclasses.fields(TrainingJobSpec)}
    assert declared == actual, (
        f"CRD spec schema drift: only-in-crd={declared - actual}, "
        f"missing-from-crd={actual - declared}"
    )
    mesh_props = set(spec_props["mesh"]["properties"])
    assert mesh_props == {f.name for f in dataclasses.fields(MeshSpec)}
    worker_props = set(spec_props["worker"]["properties"])
    assert worker_props == {
        f.name for f in dataclasses.fields(WorkerSpec)
    }


def test_crd_status_phase_enum_matches():
    _, schema = _crd_v1_schema()
    status = schema["properties"]["status"]["properties"]
    assert set(status["phase"]["enum"]) == {p.value for p in JobPhase}
    declared = set(status)
    actual = {f.name for f in dataclasses.fields(TrainingJobStatus)}
    assert actual <= declared


def test_crd_group_matches_example_manifests():
    crd, _ = _crd_v1_schema()
    group = crd["spec"]["group"]
    for rel in ("examples/ctr/job.yaml", "examples/llama/job.yaml",
                "examples/fit_a_line/job.yaml"):
        (job,) = _load_all(rel)
        api_group, version = job["apiVersion"].split("/")
        assert api_group == group, rel
        assert version in {v["name"] for v in crd["spec"]["versions"]}, rel
        assert job["kind"] == crd["spec"]["names"]["kind"], rel


def test_example_manifests_fit_crd_schema():
    """Every spec key in every example job must be declared in the CRD
    schema (k8s would reject unknown fields under structural schemas
    with pruning)."""
    _, schema = _crd_v1_schema()
    spec_props = schema["properties"]["spec"]["properties"]
    for rel in ("examples/ctr/job.yaml", "examples/llama/job.yaml",
                "examples/fit_a_line/job.yaml"):
        (job,) = _load_all(rel)
        for key, val in job["spec"].items():
            assert key in spec_props, f"{rel}: spec.{key} not in CRD"
            sub = spec_props[key]
            if isinstance(val, dict) and "properties" in sub:
                for k2 in val:
                    assert k2 in sub["properties"], f"{rel}: spec.{key}.{k2}"


def test_controller_deployment_command_parses():
    """The Deployment's command line must be accepted by the edl CLI
    argument parser (flag drift check)."""
    docs = _load_all("deploy/controller.yaml")
    (dep,) = [d for d in docs if d and d["kind"] == "Deployment"]
    container = dep["spec"]["template"]["spec"]["containers"][0]
    argv = container["command"]
    assert argv[0] == "edl"
    from edl_tpu.cli.main import build_parser

    args = build_parser().parse_args(argv[1:])
    assert args.cmd == "controller"
    assert args.max_load_desired == pytest.approx(0.9)
    assert args.kube  # in-cluster deployments must run the kube backend
    # service account must match the RBAC binding
    rbac = _load_all("deploy/rbac.yaml")
    (sa,) = [d for d in rbac if d["kind"] == "ServiceAccount"]
    assert dep["spec"]["template"]["spec"]["serviceAccountName"] == sa["metadata"]["name"]
    (binding,) = [d for d in rbac if d["kind"] == "ClusterRoleBinding"]
    assert binding["subjects"][0]["name"] == sa["metadata"]["name"]
    assert binding["subjects"][0]["namespace"] == sa["metadata"]["namespace"]


def test_rbac_grants_trainingjob_crud():
    rbac = _load_all("deploy/rbac.yaml")
    (role,) = [d for d in rbac if d["kind"] == "ClusterRole"]
    crd, _ = _crd_v1_schema()
    groups = {g for r in role["rules"] for g in r["apiGroups"]}
    assert crd["spec"]["group"] in groups


def test_style_gate_passes():
    r = subprocess.run(
        ["bash", str(REPO / "scripts" / "check_style.sh")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_worker_image_entrypoint_module_exists():
    """docker/Dockerfile.worker execs `python -m edl_tpu.runtime.worker_main`;
    the module must expose a __main__ path."""
    r = subprocess.run(
        [sys.executable, "-m", "edl_tpu.runtime.worker_main", "--help"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
