"""Perf-regression gate (scripts/perf_gate.py): synthetic improving /
regressing / noisy trajectories, the empty-trajectory bootstrap,
sentinel and config-mismatch skipping, and the committed BENCH_r*
trajectory itself (the CI phase-8 invocation, run in-process).
jax-free."""

import json
import os

from scripts.perf_gate import (
    METRICS,
    MetricSpec,
    gate,
    load_rounds,
    main,
    render,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPECS = {
    "tps": MetricSpec(+1, 0.10, "config"),
    "stall_s": MetricSpec(-1, 0.20),
}


def r(tps=None, stall=None, config="c1", rnd="r?"):
    d = {"_round": rnd, "config": config}
    if tps is not None:
        d["tps"] = tps
    if stall is not None:
        d["stall_s"] = stall
    return d


def verdict(report, metric):
    return next(v for v in report.verdicts if v.metric == metric)


def test_improving_trajectory_passes():
    traj = [r(tps=100, stall=2.0, rnd="r1"), r(tps=120, stall=1.5, rnd="r2")]
    rep = gate(traj, r(tps=130, stall=1.2), metrics=SPECS)
    assert rep.ok
    v = verdict(rep, "tps")
    assert v.status == "pass" and v.reference == 120 and v.reference_round == "r2"
    assert verdict(rep, "stall_s").reference == 1.5


def test_regression_fails_both_directions():
    traj = [r(tps=100, stall=1.0, rnd="r1")]
    rep = gate(traj, r(tps=80, stall=1.5), metrics=SPECS)
    assert not rep.ok
    assert {v.metric for v in rep.failed} == {"tps", "stall_s"}
    # renders the failures
    assert "FAIL" in render(rep)


def test_noise_within_tolerance_passes():
    traj = [r(tps=100, stall=1.0, rnd="r1")]
    rep = gate(traj, r(tps=91, stall=1.19), metrics=SPECS)  # -9% / +19%
    assert rep.ok, [v.detail for v in rep.failed]


def test_empty_trajectory_bootstraps():
    rep = gate([], r(tps=100, stall=1.0), metrics=SPECS)
    assert rep.ok
    assert {v.status for v in rep.verdicts} == {"bootstrap"}


def test_sentinel_values_are_skipped_not_passed():
    traj = [r(tps=100, rnd="r1")]
    cand = r(stall=1.0)
    cand["tps"] = -1.0  # the bench's failed-measurement sentinel
    rep = gate(traj, cand, metrics=SPECS)
    assert verdict(rep, "tps").status == "skipped"
    # a sentinel PRIOR is ignored too — never a reference of -1
    traj2 = [r(rnd="r1"), r(tps=100, rnd="r2")]
    traj2[0]["tps"] = -1.0
    rep2 = gate(traj2, r(tps=95), metrics=SPECS)
    v = verdict(rep2, "tps")
    assert v.status == "pass" and v.reference == 100


def test_config_mismatch_is_incomparable():
    # a big "regression" vs a DIFFERENT measurement config bootstraps
    traj = [r(tps=100000, config="old", rnd="r1")]
    rep = gate(traj, r(tps=100, config="new"), metrics=SPECS)
    assert verdict(rep, "tps").status == "bootstrap"
    # the real shape: BENCH_r01's llama figure predates llama_config
    traj2 = [{"_round": "r1", "tps": 100000}]  # no config key at all
    rep2 = gate(traj2, r(tps=100, config="new"), metrics=SPECS)
    assert verdict(rep2, "tps").status == "bootstrap"


def test_committed_trajectory_passes_and_synthetic_regression_fails():
    rounds = load_rounds(REPO)
    assert len(rounds) >= 5, "committed BENCH_r*.json rounds missing"
    cand, traj = rounds[-1], rounds[:-1]
    rep = gate(traj, cand)
    assert rep.ok, [v.detail for v in rep.failed]
    # the gate is not vacuous: >= 8 real comparisons happened
    assert sum(1 for v in rep.verdicts if v.status == "pass") >= 8
    # a synthetically-regressed r05 (MFU -30%, CTR -30%) must FAIL
    bad = dict(cand)
    bad["mfu"] = cand["mfu"] * 0.7
    bad["value"] = cand["value"] * 0.7
    rep2 = gate(traj, bad)
    assert {v.metric for v in rep2.failed} >= {"mfu", "value"}


def test_cli_main_json_and_exit_codes(tmp_path, capsys):
    assert main(["--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    # a regressed candidate file fails with exit 1
    rounds = load_rounds(REPO)
    bad = dict(rounds[-1])
    bad["mfu"] = bad["mfu"] * 0.5
    p = tmp_path / "BENCH_bad.json"
    p.write_text(json.dumps({"parsed": bad}))
    assert main(["--candidate", str(p)]) == 1


def test_gated_catalog_covers_the_headline_metrics():
    for name in ("value", "mfu", "decode_pct_peak_bw",
                 "reshard_stall_s", "p2p_bw_gbs", "serving_goodput_rps"):
        assert name in METRICS
    # direction sanity: stalls are lower-better
    assert METRICS["reshard_stall_s"].direction == -1
    assert METRICS["mfu"].direction == +1
