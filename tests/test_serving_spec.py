"""Fused speculative decoding (draft–verify in the horizon).

The correctness contract: with ``spec_k > 0`` the engine's greedy
output is TOKEN-IDENTICAL to sequential ``llama.generate`` for every
(K, horizon, contiguous/paged) configuration — acceptance and
rejection are invisible in the stream, only in the dispatch counts.
Plus: the host-side n-gram drafter and acceptance policy, the verify
program's donation contract, mid-verify EOS, speculation metrics, and
crash recovery mid-speculation (the recovery matrix itself lives in
tests/test_serving_recovery.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.obs import events as flight
from edl_tpu.serving import spec
from edl_tpu.serving.engine import ContinuousBatchingEngine

CFG = llama.LlamaConfig.tiny()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG)

# a prompt whose tail repeats: the n-gram drafter fires from the first
# decode step, and tiny()'s greedy continuations fall into repetitive
# attractors that keep acceptance going mid-stream
REPETITIVE = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]


def _sequential(prompt, max_new):
    toks = llama.generate(
        PARAMS, jnp.asarray([prompt], jnp.int32), CFG, max_new=max_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


# -- drafter + policy (host-side, jax-free) ---------------------------------


def test_draft_ngram_prompt_lookup():
    """Suffix n-gram lookup: longest n first, MOST RECENT earlier
    occurrence wins, continuation truncated at the context end."""
    # trailing [3, 4] occurred twice; most recent match (ending at
    # index 6) continues with [5, 6]
    ctx = [1, 2, 3, 4, 9, 3, 4, 5, 6, 3, 4]
    assert spec.draft_ngram(ctx, ngram=2, max_draft=2) == [5, 6]
    assert spec.draft_ngram(ctx, ngram=2, max_draft=4) == [5, 6, 3, 4]
    # no repeated suffix at any n: no draft
    assert spec.draft_ngram([1, 2, 3, 4, 5], ngram=3, max_draft=4) == []
    # 1-gram fallback when no longer n-gram repeats
    assert spec.draft_ngram([7, 1, 8, 1], ngram=3, max_draft=2) == [8, 1]
    # degenerate contexts draft nothing
    assert spec.draft_ngram([], 3, 4) == []
    assert spec.draft_ngram([5], 3, 4) == []
    assert spec.draft_ngram([5, 5], 3, 0) == []


def test_spec_policy_warmup_then_disable():
    """Below ``warmup`` drafted tokens every request drafts; past it a
    request under ``min_accept`` is disabled permanently, and
    ``forget`` drops its counters."""
    pol = spec.SpecPolicy(min_accept=0.5, warmup=8)
    assert pol.should_draft("a")  # no data: draft
    pol.observe("a", drafted=4, accepted=0)
    assert pol.should_draft("a")  # 4 < warmup: still probing
    pol.observe("a", drafted=4, accepted=0)
    assert not pol.should_draft("a")  # 0/8 < 0.5: disabled
    pol.observe("b", drafted=16, accepted=12)
    assert pol.should_draft("b")  # 12/16 >= 0.5
    pol.forget("a")
    assert pol.should_draft("a")  # fresh request id: probe again
    # min_accept <= 0 never disables, whatever the history
    free = spec.SpecPolicy(min_accept=0.0, warmup=1)
    free.observe("c", drafted=100, accepted=0)
    assert free.should_draft("c")


def test_spec_engine_validation():
    with pytest.raises(ValueError, match="spec_k"):
        ContinuousBatchingEngine(PARAMS, CFG, spec_k=-1)
    with pytest.raises(ValueError, match="temperature"):
        ContinuousBatchingEngine(PARAMS, CFG, spec_k=2, temperature=0.7)
    with pytest.raises(ValueError, match="spec_ngram"):
        ContinuousBatchingEngine(PARAMS, CFG, spec_k=2, spec_ngram=0)


# -- token identity ----------------------------------------------------------


@pytest.mark.parametrize("paged", [False, True], ids=["contig", "paged"])
@pytest.mark.parametrize("horizon", [1, 4])
@pytest.mark.parametrize("spec_k", [2, 4, 8])
def test_spec_greedy_token_identity(spec_k, horizon, paged):
    """The speculation acceptance contract: for every draft width K,
    horizon, and cache layout, greedy tokens are exactly sequential
    ``generate``'s — for repetitive traffic (drafts accept), arbitrary
    traffic (drafts reject), and requests joining mid-stream while
    slot-mates are mid-speculation."""
    prompts = [list(REPETITIVE), [5, 6, 7, 8, 9, 10], [3] * 8]
    max_news = [17, 11, 13]  # not divisible by K or horizon
    kw = {"block_size": 8, "pool_blocks": 64} if paged else {}
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=96, horizon=horizon,
        spec_k=spec_k, spec_ngram=3, **kw,
    )
    eng.submit("r0", prompts[0], max_news[0])
    eng.submit("r1", prompts[1], max_news[1])
    eng.step()  # r2 joins while r0/r1 are mid-speculation
    eng.submit("r2", prompts[2], max_news[2])
    res = eng.run()
    for i in range(3):
        assert res[f"r{i}"].tokens == _sequential(prompts[i], max_news[i]), (
            f"r{i} diverged at spec_k={spec_k} h={horizon} paged={paged}"
        )
        assert res[f"r{i}"].outcome == "done"


def test_spec_midstream_join_evict_token_identity():
    """Short-budget requests finishing (evict) while long repetitive
    ones keep speculating, with late joins landing in freed slots —
    every stream still matches sequential."""
    prompts = [list(REPETITIVE), [9, 10], [4] * 6, list(REPETITIVE),
               [11, 12, 13], [2, 5, 2, 5, 2, 5]]
    max_news = [15, 2, 7, 9, 3, 11]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=3, max_len=96, horizon=1, spec_k=4,
    )
    for i in range(4):
        eng.submit(f"r{i}", prompts[i], max_news[i])
    for _ in range(3):
        eng.step()
    for i in range(4, 6):
        eng.submit(f"r{i}", prompts[i], max_news[i])
    res = eng.run()
    assert set(res) == {f"r{i}" for i in range(6)}
    for i in range(6):
        assert res[f"r{i}"].tokens == _sequential(prompts[i], max_news[i]), (
            f"r{i}"
        )


def test_spec_mid_verify_eos():
    """EOS landing INSIDE an accepted run terminates the row
    mid-verify on device: the EOS token is the last emitted, later
    accepted lanes (and the bonus token) are discarded, and the
    outcome is "eos" — while a slot-mate speculates through the same
    dispatch unaffected."""
    full = _sequential(REPETITIVE, 20)
    # pick an EOS deep enough that speculation is mid-run when it hits
    eos = full[6]
    want = full[:full.index(eos) + 1]
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=96, horizon=1, spec_k=4,
    )
    eng.submit("stops", list(REPETITIVE), 20, eos_id=eos)
    eng.submit("runs", [3] * 8, 13)
    res = eng.run()
    assert res["stops"].tokens == want
    assert res["stops"].outcome == "eos"
    assert res["runs"].tokens == _sequential([3] * 8, 13)
    assert res["runs"].outcome == "done"


def test_spec_zero_acceptance_streak_stays_identical():
    """A stream whose drafts NEVER accept (policy disabled after
    warmup, sentinel lanes thereafter) still emits exactly sequential
    tokens — a rejected verify commits precisely one plain greedy
    step, and the disable flips nothing but dispatch shape."""
    prompt = list(range(20, 29))  # non-repetitive: drafter rarely right
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=1, max_len=96, horizon=1,
        spec_k=4, spec_min_accept=1.1, spec_ngram=3,
    )
    # min_accept > 1 disables every request the moment warmup ends —
    # the permanent-disable path, not just low acceptance
    eng._spec_policy.warmup = 4
    eng.submit("r0", prompt, 24)
    res = eng.run()
    assert res["r0"].tokens == _sequential(prompt, 24)
    snap = eng.metrics.snapshot()
    # the policy actually disabled drafting: drafting stopped at/near
    # warmup instead of riding the whole 24-token stream
    assert snap["spec_drafted"] <= 12


# -- donation ---------------------------------------------------------------


def test_spec_verify_program_donates_cache():
    """The verify dispatch keeps the in-place update chain: kc/vc and
    the slot-state vectors are donated (stale references dead, buffer
    reused), same contract as the block program."""
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=64, horizon=1, spec_k=4,
    )
    eng.submit("r0", list(REPETITIVE), 12)
    eng.step()  # prefill + first speculative iteration
    kc0, vc0 = eng._kc, eng._vc
    ptr0 = kc0.unsafe_buffer_pointer()
    eng.step()  # at least one more verify dispatch consumes kc0/vc0
    assert eng._donates is True
    assert kc0.is_deleted() and vc0.is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(kc0)
    assert eng._kc.unsafe_buffer_pointer() == ptr0
    res = eng.run()
    assert res["r0"].tokens == _sequential(REPETITIVE, 12)
    assert eng.metrics.snapshot()["dispatches_verify"] >= 1


# -- metrics + observability ------------------------------------------------


def test_spec_metrics_and_flight_events():
    """A repetitive stream drafts and accepts: the spec counters move,
    the snapshot exposes the acceptance rate, accepted tokens per
    decode-phase dispatch beats 1.0, and each drained verify block
    leaves a ``serve.verify`` flight event with the per-rid accepted
    run length."""
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving.metrics import ServingMetrics

    flight.reset_default_recorder()
    eng = ContinuousBatchingEngine(
        PARAMS, CFG, max_slots=2, max_len=96, horizon=1, spec_k=4,
        metrics=ServingMetrics(registry=MetricsRegistry()),
    )
    eng.submit("r0", list(REPETITIVE), 40)
    res = eng.run()
    assert res["r0"].tokens == _sequential(REPETITIVE, 40)
    snap = eng.metrics.snapshot()
    assert snap["spec_drafted"] > 0
    assert snap["spec_accepted"] > 0
    assert 0 < snap["spec_acceptance_rate"] <= 1.0
    assert snap["spec_acceptance_rate"] == pytest.approx(
        snap["spec_accepted"] / snap["spec_drafted"]
    )
    assert snap["dispatches_verify"] > 0
    # the point of the whole machinery: more than one token lands per
    # decode-phase dispatch on repetitive traffic
    decode_dispatches = snap["dispatches_verify"] + snap["dispatches_decode"]
    assert snap["tokens_out"] / decode_dispatches > 1.0
    evs = [
        r for r in flight.default_recorder().records()
        if r["kind"] == "serve.verify"
    ]
    assert evs, "no serve.verify flight events recorded"
    assert all(e["corr"]["rid"] == "r0" for e in evs)
    assert sum(e["attrs"]["accepted"] for e in evs) == snap["spec_accepted"]
    assert sum(e["attrs"]["drafted"] for e in evs) == snap["spec_drafted"]
    assert all(e["attrs"]["emitted"] >= e["attrs"]["accepted"] for e in evs)
    # the prometheus twins carry the same counts
    m = eng.metrics
    assert m._m_spec_drafted.value() == snap["spec_drafted"]
    assert m._m_spec_accepted.value() == snap["spec_accepted"]
    assert m._m_spec_rate.value() == pytest.approx(
        snap["spec_acceptance_rate"]
    )


def test_spec_disabled_is_zero_overhead():
    """``spec_k=0`` leaves the engine byte-for-byte on the horizon
    path: identical dispatch counts to an engine that never heard of
    speculation, zero verify dispatches, zero spec counters."""
    def counts(**kw):
        eng = ContinuousBatchingEngine(
            PARAMS, CFG, max_slots=2, max_len=64, horizon=4, **kw
        )
        eng.submit("a", [2, 3, 4], 9)
        eng.submit("b", [5, 6], 7)
        res = eng.run()
        return eng.metrics.snapshot(), {r: res[r].tokens for r in res}

    base_snap, base_toks = counts()
    off_snap, off_toks = counts(spec_k=0, spec_ngram=5, spec_min_accept=0.9)
    assert off_toks == base_toks
    for k in ("dispatches_decode", "dispatches_prefill",
              "dispatches_verify", "tokens_out", "dispatches_per_token"):
        assert off_snap[k] == base_snap[k], k
    assert off_snap["spec_drafted"] == 0
    assert off_snap["spec_acceptance_rate"] == 0.0


def test_spec_multi_token_drain_records_honest_itl():
    """Satellite: a verify drain landing k tokens at once goes through
    the SAME honest-tail ITL accounting as a horizon block — one full
    inter-drain gap + k-1 zeros, so p99 still sees the stall while
    count/sum match the per-token view (PR 6 convention)."""
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving.metrics import ServingMetrics

    t = [0.0]
    m = ServingMetrics(clock=lambda: t[0], registry=MetricsRegistry())
    m.on_submit("r")
    m.on_pop("r")
    m.on_admit("r", 4)
    t[0] = 1.0
    m.on_tokens("r", 1)       # first token: TTFT, no ITL yet
    t[0] = 1.5
    m.on_tokens("r", 4)       # verify drain lands 4 tokens
    st = m.itl_hist.stats()
    assert st["count"] == 4   # one gap + three zeros
    assert st["sum"] == pytest.approx(0.5)
    assert m.itl_hist.percentile(0.99) >= 0.25  # the stall shows at p99


def test_top_serving_strip_shows_acceptance():
    """`edl top` renders a spec line (live acceptance rate) only when
    the scraped engine actually drafted — quiet otherwise."""
    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.obs.top import summarize

    r = obs_metrics.MetricsRegistry()
    r.counter("edl_serving_tokens_total", "").inc(40)
    r.counter("edl_serving_dispatch_total", "", ("kind",)).inc(
        10, kind="verify"
    )
    fams = obs_metrics.parse_prometheus_text(r.render())
    assert not any("spec accept" in l for l in summarize(fams))
    r.counter("edl_serving_spec_drafted_total", "").inc(32)
    r.counter("edl_serving_spec_accepted_total", "").inc(24)
    fams = obs_metrics.parse_prometheus_text(r.render())
    (line,) = [l for l in summarize(fams) if "spec accept" in l]
    assert "75.0%" in line and "drafted=32" in line and "accepted=24" in line
