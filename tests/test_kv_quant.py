"""Quantized paged KV cache (``--kv-quant int8/int4``).

The contract under test: the OFF lane stays byte-identical to the
unquantized paged engine (same tokens, same dispatch counters, no
quantized program keys), the quantized lanes store int8/int4 blocks +
per-(block, kv-head) f32 scales whose round-trip error is bounded by
the quantization step, copy-on-write carries a block's scales with its
values, crash recovery replays within tolerance, and the speculative
acceptance guard flags an injected quality regression.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.serving import engine as engine_mod
from edl_tpu.serving.engine import ContinuousBatchingEngine, SpecAcceptGuard
from edl_tpu.utils import faults

CFG = llama.LlamaConfig.tiny()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG)

PROMPTS = [list(range(2, 2 + n)) for n in (4, 7, 3, 9, 5, 6)]
MAX_NEWS = [6, 3, 13, 5, 7, 9]


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _engine(**kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 8)
    return ContinuousBatchingEngine(PARAMS, CFG, **kw)


def _run_all(eng, reqs=None):
    reqs = reqs if reqs is not None else list(zip(PROMPTS, MAX_NEWS))
    for i, (p, mn) in enumerate(reqs):
        eng.submit(f"r{i}", p, mn)
    res = eng.run()
    return [res[f"r{i}"].tokens for i in range(len(reqs))]


def _agreement(a, b):
    n = max(len(a), len(b))
    return sum(x == y for x, y in zip(a, b)) / n if n else 1.0


# -- store/unpack round-trip ---------------------------------------------------


@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
def test_kvq_store_roundtrip_error_bound(kv_quant):
    """Decode-order writes into one block: dequantized content tracks
    the written f32 values within the quantization step at the block's
    final scale (the last write exactly; earlier offsets accumulate at
    most half a step per rescale as the block's amax grew)."""
    rng = np.random.RandomState(3)
    L, nb, bs, kvh, hd = 2, 5, 8, 2, 16
    hdp = llama.kvq_packed_head_dim(kv_quant, hd)
    pool = jnp.zeros((L, nb, bs, kvh, hdp), jnp.int8)
    scale = jnp.zeros((L, nb, kvh), jnp.float32)
    vals = rng.randn(bs, kvh, hd).astype(np.float32)
    for off in range(bs):
        pool, scale = llama._kvq_store(
            pool, scale, 0,
            jnp.asarray([1], jnp.int32), jnp.asarray([off], jnp.int32),
            jnp.asarray(vals[off][None]), kv_quant,
        )
    sc = np.asarray(scale[0, 1])  # [kvh]
    assert np.all(sc > 0)
    deq = np.asarray(
        llama._kvq_unpack(pool[0, 1], kv_quant)
    ) * sc[None, :, None]
    step = sc[None, :, None]
    # last write: a single quantization at the final (largest) scale
    assert np.all(np.abs(deq[-1] - vals[-1]) <= 0.5 * step[0] + 1e-6)
    # earlier offsets: + at most half a step per intervening rescale
    assert np.all(np.abs(deq - vals) <= (0.5 * bs) * step + 1e-6)
    # per-head scale actually covers the block's absmax
    assert np.all(
        np.abs(vals).max(axis=(0, 2))
        <= sc * llama._KVQ_QMAX[kv_quant] * (1 + 1e-6)
    )


def test_kvq_store_fresh_block_resets_scale():
    """A write at offset 0 marks the block FRESH: the previous tenant's
    large scale is dropped (not inherited) and its stale content reads
    back as zero instead of garbage under the new scale."""
    L, nb, bs, kvh, hd = 1, 3, 4, 2, 8
    pool = jnp.zeros((L, nb, bs, kvh, hd), jnp.int8)
    scale = jnp.zeros((L, nb, kvh), jnp.float32)
    big = jnp.full((1, kvh, hd), 100.0, jnp.float32)
    for off in range(bs):  # old tenant fills block 1 with huge values
        pool, scale = llama._kvq_store(
            pool, scale, 0, jnp.asarray([1], jnp.int32),
            jnp.asarray([off], jnp.int32), big, "int8",
        )
    small = jnp.full((1, kvh, hd), 0.5, jnp.float32)
    pool, scale = llama._kvq_store(
        pool, scale, 0, jnp.asarray([1], jnp.int32),
        jnp.asarray([0], jnp.int32), small, "int8",
    )
    sc = np.asarray(scale[0, 1])
    assert np.all(sc == pytest.approx(0.5 / 127.0))  # reset, not 100/127
    deq = np.asarray(llama._kvq_unpack(pool[0, 1], "int8"))
    assert np.all(deq[1:] == 0)  # stale offsets zeroed
    assert np.asarray(deq[0] * sc[:, None]) == pytest.approx(0.5, abs=1e-5)


def test_kvq_int4_needs_even_head_dim():
    with pytest.raises(ValueError, match="even head_dim"):
        llama.kvq_packed_head_dim("int4", 5)
    assert llama.kvq_packed_head_dim("int4", 16) == 8
    assert llama.kvq_packed_head_dim("int8", 16) == 16


# -- the OFF lane is byte-identical --------------------------------------------


def test_kv_quant_off_byte_identical():
    """``kv_quant="off"`` is the same engine, not a quantized engine
    with a wide tolerance: identical tokens, identical dispatch
    counters, float pools, no scale planes, and no quantized program
    ever memoized under an "off" key."""
    plain = _engine(horizon=4)
    off = _engine(horizon=4, kv_quant="off")
    toks_plain = _run_all(plain)
    toks_off = _run_all(off)
    assert toks_plain == toks_off
    s1, s2 = plain.metrics.snapshot(), off.metrics.snapshot()
    for k in ("dispatches_decode", "dispatches_prefill", "tokens_out"):
        assert s1[k] == s2[k], k
    assert off._ks is None and off._vs is None
    assert off._kc.dtype == plain._kc.dtype != jnp.int8
    assert off._kvq_guard is None
    qkeys = [
        k for k in engine_mod._programs
        if isinstance(k, tuple) and str(k[0]).endswith("-q")
    ]
    assert all(k[1] != "off" for k in qkeys)


def test_kv_quant_constructor_validation():
    with pytest.raises(ValueError, match="kv_quant"):
        ContinuousBatchingEngine(PARAMS, CFG, max_len=64, kv_quant="int8")
    with pytest.raises(ValueError, match="kv_quant"):
        _engine(kv_quant="fp8")


# -- quantized lanes: quality, pool dtype, ledger ------------------------------


@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
def test_kv_quant_pool_layout_and_ledger(kv_quant):
    """Quantized pools are int8 with the packed head dim; the memory
    ledger's kv category and bytes-per-token gauge report the REAL
    (values + scales) figure, 2-4x under the float pool."""
    from edl_tpu.obs import memledger
    from edl_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.reset_default_registry()
    memledger.reset_default_ledger(reg)
    try:
        eng = _engine(kv_quant=kv_quant)
        hdp = llama.kvq_packed_head_dim(kv_quant, CFG.head_dim)
        assert eng._kc.dtype == jnp.int8
        assert eng._kc.shape[-1] == hdp
        assert eng._ks.shape == (
            CFG.n_layers, eng.pool_blocks, CFG.n_kv_heads
        )
        pool_b = (
            eng._kc.nbytes + eng._vc.nbytes + eng._ks.nbytes
            + eng._vs.nbytes
        )
        assert reg.get("edl_hbm_bytes").value(category="kv") == pool_b
        cap = eng.pool_blocks * eng.block_size
        assert reg.get("edl_kv_bytes_per_token").value() == pytest.approx(
            pool_b / cap
        )
        # the whole point: fewer bytes than the float pool would hold
        el = np.dtype(CFG.dtype).itemsize
        float_b = (
            2 * CFG.n_layers * eng.pool_blocks * eng.block_size
            * CFG.n_kv_heads * CFG.head_dim * el
        )
        assert float_b / pool_b >= 1.8, (float_b, pool_b)
    finally:
        memledger.reset_default_ledger(obs_metrics.reset_default_registry())


def test_kv_quant_int8_output_quality():
    """int8-KV greedy streams track the float paged engine's within a
    pinned fractional-token tolerance (exact identity is not the
    contract — near-tied logits may flip — but wholesale divergence
    means the dequant discipline broke)."""
    toks_f = _run_all(_engine(horizon=4))
    toks_q = _run_all(_engine(horizon=4, kv_quant="int8"))
    agr = [_agreement(a, b) for a, b in zip(toks_f, toks_q)]
    assert np.mean(agr) >= 0.9, agr
    for t in toks_q:
        assert len(t) > 0


def test_kv_quant_int4_runs_to_completion():
    """int4 is the same machinery at half the bytes: noisier (no
    agreement pin) but every request must complete with its full
    budget or a real EOS."""
    eng = _engine(kv_quant="int4")
    toks = _run_all(eng)
    for t, mn in zip(toks, MAX_NEWS):
        assert 0 < len(t) <= mn
    assert eng._balloc.allocated_blocks == 0


# -- copy-on-write carries scales ----------------------------------------------


def test_cow_block_copy_carries_scales():
    """The quantized CoW program copies the block's SCALES with its
    values — a copied block that kept stale scales would dequantize
    to garbage."""
    eng = _engine(max_slots=2, kv_quant="int8", prefix_cache=True)
    kc = eng._kc.at[:, 3].set(5)
    vc = eng._vc.at[:, 3].set(-3)
    ks = eng._ks.at[:, 3].set(0.25)
    vs = eng._vs.at[:, 3].set(0.5)
    kc, vc, ks, vs = eng._copyblk(
        kc, vc, ks, vs, jnp.int32(3), jnp.int32(4)
    )
    assert np.all(np.asarray(kc[:, 4]) == 5)
    assert np.all(np.asarray(vc[:, 4]) == -3)
    assert np.all(np.asarray(ks[:, 4]) == 0.25)
    assert np.all(np.asarray(vs[:, 4]) == 0.5)
    assert np.all(np.asarray(ks[:, 2]) == 0.0)  # only the dst block moved


def test_prefix_full_hit_cow_identical_under_int8():
    """An identical prompt served from the prefix cache (full-chain
    hit -> CoW of the last block) reads the SAME quantized blocks the
    first request wrote: the two greedy streams must match exactly —
    any scale lost in the copy would split them immediately."""
    prompt = list(range(2, 26))  # three full 8-blocks
    eng = _engine(kv_quant="int8", prefix_cache=True)
    eng.submit("one", prompt, 7)
    res = eng.run()
    eng.submit("two", prompt, 7)
    res2 = eng.run()
    assert res2["two"].tokens == res["one"].tokens
    assert eng._prefix.hits >= 3


# -- crash recovery ------------------------------------------------------------


@pytest.mark.parametrize("plan", [
    "serve.dispatch:raise@n=2",
    "serve.prefill:raise@n=1",
])
def test_int8_recovery_replay_within_tolerance(plan):
    """Crash recovery rebuilds the quantized pool + scale planes from
    host truth and replays resident tokens through the quantized
    prefill. Replay quantizes whole blocks under their final amax
    while the original run grew scales incrementally, so exact
    identity is not guaranteed — but streams must stay within the
    pinned agreement tolerance of a fault-free quantized run."""
    base = _run_all(_engine(kv_quant="int8", horizon=4))
    faults.arm(plan, seed=0)
    eng = _engine(kv_quant="int8", horizon=4, max_recoveries=3)
    toks = _run_all(eng)
    faults.disarm()
    assert eng.recoveries >= 1
    assert eng._kc.dtype == jnp.int8  # rebuilt pool is still quantized
    agr = [_agreement(a, b) for a, b in zip(base, toks)]
    assert np.mean(agr) >= 0.8, (plan, agr)
    for t, mn in zip(toks, MAX_NEWS):
        assert 0 < len(t) <= mn


# -- the speculative-acceptance quality gate -----------------------------------


def test_spec_accept_guard_fires_on_injected_regression():
    from edl_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.MetricsRegistry()
    g = SpecAcceptGuard(reg, warmup=5, tol=0.05, alpha=0.5)
    gauge = reg.get("edl_kv_quant_quality_ok")
    assert gauge.value() == 1.0
    g.observe(0, 0)  # no drafts: ignored, not a 0% observation
    for _ in range(5):
        g.observe(10, 8)
    assert g.baseline == pytest.approx(0.8)
    assert g.ok and gauge.value() == 1.0
    for _ in range(10):  # injected regression: acceptance collapses
        g.observe(10, 2)
    assert not g.ok and gauge.value() == 0.0
    assert g.ema < g.baseline - g.tol
    for _ in range(30):  # and the flag clears when quality returns
        g.observe(10, 8)
    assert g.ok and gauge.value() == 1.0


def test_engine_wires_guard_only_for_quantized_spec():
    e = _engine(kv_quant="int8", spec_k=2, spec_ngram=2)
    assert e._kvq_guard is not None
    assert _engine(spec_k=2, spec_ngram=2)._kvq_guard is None
    assert _engine(kv_quant="int8")._kvq_guard is None


def test_int8_spec_decoding_accepts_and_observes():
    """Speculation composes with the quantized cache: a repetitive
    prompt yields real acceptances, and every verify block feeds the
    quality guard's EMA."""
    eng = _engine(max_slots=1, kv_quant="int8", spec_k=4, spec_ngram=3,
                  horizon=1)
    eng.submit("rep", [5, 9] * 6, 24)
    eng.run()
    snap = eng.metrics.snapshot()
    assert snap["spec_drafted"] > 0
    assert snap["spec_accepted"] > 0
    assert eng._kvq_guard is not None and eng._kvq_guard.ema is not None
