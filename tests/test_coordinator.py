"""Coordinator: native C++ core (ctypes), TCP server, Python fallback,
and the worker bootstrap protocol over it."""

import threading
import time

import pytest

from edl_tpu.runtime import coordinator as coord_mod
from edl_tpu.runtime.coordinator import (
    CoordinatorServer,
    PyCoordinator,
    ensure_native_built,
)
from edl_tpu.runtime.entrypoint import (
    FailureGateError,
    bootstrap,
    check_failure_gate,
    record_failure,
    run_worker,
)

HAVE_NATIVE = ensure_native_built()

BACKENDS = ["py"] + (["native"] if HAVE_NATIVE else [])


def make(backend, ttl=10.0):
    if backend == "native":
        return coord_mod.NativeCoordinator(ttl)
    return PyCoordinator(ttl)


@pytest.mark.parametrize("backend", BACKENDS)
def test_kv_and_membership(backend):
    c = make(backend)
    c.kv_put("a", "hello world")
    assert c.kv_get("a") == "hello world"
    c.kv_del("a")
    assert c.kv_get("a") is None

    e0 = c.register("w1", 1)
    e1 = c.register("w0", 1)
    assert e1 > e0
    ms = c.members()
    # deterministic rank: sorted by name (reference: k8s_tools fetch_pod_id)
    assert [(m.name, m.rank) for m in ms] == [("w0", 0), ("w1", 1)]
    assert c.heartbeat("w0")
    assert not c.heartbeat("ghost")
    # zombie with stale incarnation is ignored
    c.register("w0", 5)
    e_before = c.epoch()
    c.register("w0", 3)
    assert c.epoch() == e_before
    e2 = c.leave("w1")
    assert e2 > e1
    assert len(c.members()) == 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_member_ttl_expiry_bumps_epoch(backend):
    c = make(backend, ttl=0.05)
    c.register("w0", 1)
    e = c.epoch()
    time.sleep(0.08)
    assert c.expire() > e
    assert c.members() == []


@pytest.mark.parametrize("backend", BACKENDS)
def test_task_queue_parity(backend):
    # the native queue must behave exactly like runtime/data.py
    c = make(backend)
    c.queue_init(100, 10, passes=2, lease_timeout_s=16.0)
    seen = 0
    while (t := c.lease("w0")) is not None:
        seen += 1
        assert c.ack(t.task_id)
    assert seen == 20  # 10 chunks x 2 passes
    assert c.queue_done()
    stats = c.queue_stats()
    assert stats["done"] == 20 and stats["todo"] == 0


@pytest.mark.parametrize("backend", BACKENDS)
def test_queue_release_worker(backend):
    c = make(backend)
    c.queue_init(30, 10)
    t0 = c.lease("w0")
    t1 = c.lease("w1")
    assert c.release_worker("w0") == 1
    got = set()
    while (t := c.lease("w1")) is not None:
        got.add(t.start)
        c.ack(t.task_id)
    c.ack(t1.task_id)
    assert t0.start in got


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_wal_recovery_exact_state(tmp_path):
    """Durability (VERDICT r2 #2): a coordinator rebuilt from its WAL
    resumes with exact KV, epoch, incarnations, barriers, and queue
    accounting — the etcd-durability analog (reference:
    pkg/jobparser.go:167-184)."""
    wal = str(tmp_path / "c.wal")
    c = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    c.kv_put("dist", "10.0.0.1:7164")
    c.kv_put("gone", "x")
    c.kv_del("gone")
    c.register("w0", 1)
    c.register("w1", 2)
    c.barrier_arrive("start", "w0")
    c.queue_init(100, 10, passes=2, lease_timeout_s=16.0)
    t1, t2 = c.lease("w0"), c.lease("w1")
    c.ack(t1.task_id)
    c.nack(t2.task_id)
    before = (c.epoch(), c.queue_stats(),
              [(m.name, m.incarnation, m.rank) for m in c.members()])
    c.close()

    r = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    assert r.kv_get("dist") == "10.0.0.1:7164"
    assert r.kv_get("gone") is None
    assert (r.epoch(), r.queue_stats(),
            [(m.name, m.incarnation, m.rank) for m in r.members()]) == before
    assert r.barrier_count("start") == 1
    # drain both passes through the recovered instance: exact accounting
    while True:
        t = r.lease("w0")
        if t is None:
            break
        r.ack(t.task_id)
    assert r.queue_done()
    assert r.queue_stats()["done"] == 20  # 10 chunks x 2 passes, no loss
    r.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_server_sigkill_restart_resumes_and_clients_reconnect(tmp_path):
    """The TCP coordinator is SIGKILLed mid-queue and restarted on the
    same port: it recovers from the WAL and existing clients reconnect
    transparently (backoff re-dial inside CoordinatorClient)."""
    wal = str(tmp_path / "srv.wal")
    with CoordinatorServer(member_ttl_s=5.0, wal_path=wal) as srv:
        c = srv.client()
        c.kv_put("k", "v1")
        c.register("w0", 1)
        c.queue_init(40, 10, 1, 16.0)
        t = c.lease("w0")
        assert c.ack(t.task_id)
        srv.kill()  # SIGKILL, no graceful shutdown
        srv.restart()
        # same client object: reconnects and sees recovered state
        assert c.kv_get("k") == "v1"
        assert c.queue_stats()["done"] == 1
        done = 1
        while True:
            t = c.lease("w0")
            if t is None:
                break
            assert c.ack(t.task_id)
            done += 1
        assert done == 4 and c.queue_done()
        c.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_tcp_server_end_to_end():
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        c1 = srv.client()
        c2 = srv.client()
        assert c1.ping()
        c1.kv_put("discovery", "10.0.0.1:7164 10.0.0.2:7164")
        assert c2.kv_get("discovery") == "10.0.0.1:7164 10.0.0.2:7164"
        c1.register("host-a", 1)
        c2.register("host-b", 1)
        ms = c2.members()
        assert [(m.name, m.rank) for m in ms] == [("host-a", 0), ("host-b", 1)]
        c1.queue_init(64, 16, 1, 16.0)
        t = c2.lease("host-b")
        assert t is not None and (t.start, t.end) == (0, 16)
        assert c2.ack(t.task_id)
        c1.close()
        c2.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_worker_bootstrap_over_tcp():
    # two workers bootstrap concurrently against the native server:
    # barrier holds until both arrive, ranks are deterministic.
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        results = {}
        both_bootstrapped = threading.Barrier(2)

        def worker(wid):
            c = srv.client()
            env = {
                "EDL_JOB_NAME": "demo",
                "EDL_WORKER_ID": wid,
                "EDL_WORKERS": "2",
                "EDL_WORKERS_MIN": "2",
                "EDL_FAULT_TOLERANT": "1",
            }
            ctx = bootstrap(c, env, barrier_timeout_s=10.0)
            results[wid] = ctx
            both_bootstrapped.wait(timeout=10)  # hold membership steady
            code = run_worker(ctx, lambda ctx: 0)
            assert code == 0
            c.close()

        threads = [
            threading.Thread(target=worker, args=(w,)) for w in ("wb", "wa")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert results["wa"].rank == 0
        assert results["wb"].rank == 1
        assert results["wa"].world_size == 2
        # both left cleanly
        c = srv.client()
        assert c.members() == []
        c.close()


def test_failure_gate():
    c = PyCoordinator()
    check_failure_gate(c, "j", fault_tolerant=True, budget=2)
    record_failure(c, "j", "segfault")
    record_failure(c, "j", "abort")
    check_failure_gate(c, "j", True, budget=2)  # at budget: still ok
    record_failure(c, "j", "oom")
    with pytest.raises(FailureGateError):
        check_failure_gate(c, "j", True, budget=2)
    # non-FT: any failure trips the gate
    with pytest.raises(FailureGateError):
        check_failure_gate(c, "j", False, budget=2)


def test_incarnation_monotonic_across_restarts():
    c = PyCoordinator()
    env = {
        "EDL_JOB_NAME": "j",
        "EDL_WORKER_ID": "w0",
        "EDL_WORKERS_MIN": "1",
        "EDL_FAULT_TOLERANT": "1",
    }
    ctx1 = bootstrap(c, env, barrier_timeout_s=1.0)
    assert ctx1.incarnation == 1
    run_worker(ctx1, lambda ctx: 0)
    ctx2 = bootstrap(c, env, barrier_timeout_s=1.0)
    assert ctx2.incarnation == 2  # restart gets a fresh incarnation


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_wal_compaction_bounds_bytes(tmp_path):
    """The WAL must stay O(state), not O(history) (VERDICT r3 weak #3):
    once appended bytes cross the compaction threshold the coordinator
    snapshots its full state and truncates, so a long job's restart
    replays a snapshot + short suffix instead of its entire mutation
    history. The bound holds THROUGHOUT a soak of step-scoped KV churn
    and queue traffic, and recovery from snapshot+suffix is exact."""
    import os

    wal = str(tmp_path / "c.wal")
    c = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    c.set_wal_compact_bytes(8192)
    c.register("w0", 1)
    c.register("w1", 2)
    c.queue_init(6400, 32, passes=1, lease_timeout_s=16.0)
    # soak: 200 tasks x (lease + 2 KV puts + ack) ≈ 25 KB of raw WAL
    # traffic — several compactions at an 8 KB threshold
    while True:
        t = c.lease("w0")
        if t is None:
            break
        c.kv_put("go/0", f"{t.task_id}:step")
        c.kv_put(f"ckmark/{t.task_id % 7}", "x")
        c.ack(t.task_id)
        # bound: snapshot(state ≈ 200 task lines ≈ 5 KB) + threshold
        assert os.path.getsize(wal) < 8192 + 8192, os.path.getsize(wal)
    stats = c.wal_stats()
    assert stats["compactions"] >= 1, stats
    assert c.queue_done()
    before = (
        c.epoch(),
        c.queue_stats(),
        [(m.name, m.incarnation, m.rank) for m in c.members()],
        c.kv_get("go/0"),
    )
    # explicit compact + post-snapshot suffix: recovery must see both
    c.wal_compact()
    c.kv_put("after_snapshot", "1")
    c.close()

    r = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    assert (
        r.epoch(),
        r.queue_stats(),
        [(m.name, m.incarnation, m.rank) for m in r.members()],
        r.kv_get("go/0"),
    ) == before
    assert r.kv_get("after_snapshot") == "1"
    assert r.queue_done()
    r.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_wal_names_with_framing_chars_survive_restart(tmp_path):
    """Worker/barrier names are arbitrary strings on the in-process
    ctypes path (ADVICE r3): names containing the WAL's framing
    characters (space, newline, backslash) must replay exactly, in
    membership records, barrier arrivals, lease grants, and snapshots."""
    wal = str(tmp_path / "c.wal")
    weird = "w 0\nback\\slash\ttab\rcr"
    c = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    c.register(weird, 1)
    c.register("plain", 1)
    c.barrier_arrive("bar rier\n", weird)
    c.queue_init(64, 32, passes=1, lease_timeout_s=16.0)
    t = c.lease(weird)
    assert t is not None
    before = [(m.name, m.incarnation, m.rank) for m in c.members()]
    c.close()

    r = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    assert [(m.name, m.incarnation, m.rank) for m in r.members()] == before
    assert r.barrier_count("bar rier\n") == 1
    # snapshot path: compact with the weird-named LEASE still live (the
    # snapshot's SL record carries the name), reopen again
    r.wal_compact()
    assert r.wal_stats()["compactions"] == 1
    r.kv_put("tick", "1")  # post-snapshot suffix
    r.close()
    s = coord_mod.NativeCoordinator(5.0, wal_path=wal)
    assert [(m.name, m.incarnation, m.rank) for m in s.members()] == before
    assert s.barrier_count("bar rier\n") == 1
    # the lease survived snapshot+replay under the weird worker:
    # releasing that worker requeues exactly one task
    assert s.release_worker(weird) == 1
    s.close()
