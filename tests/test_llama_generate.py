"""KV-cache decode (llama.generate) — the serving half of the export
story. Oracle: iterative full-forward greedy decoding must produce the
same tokens as the cached scan."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.runtime.export import export_params, load_export


def _oracle_greedy(params, tokens, cfg, max_new):
    toks = jnp.asarray(tokens)
    out = []
    for _ in range(max_new):
        logits = llama.forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_generate_matches_full_forward_oracle():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab
    got = llama.generate(params, jnp.asarray(prompt), cfg, max_new=6)
    want = _oracle_greedy(params, prompt, cfg, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_from_export(tmp_path):
    """A fresh consumer: load the published export, generate — no
    TrainState, optimizer, or mesh."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    export_params(str(tmp_path), params, step=3, dtype="float32")
    loaded, _ = load_export(str(tmp_path))
    prompt = np.ones((1, 4), np.int32)
    got = llama.generate(loaded, jnp.asarray(prompt), cfg, max_new=5)
    want = llama.generate(params, jnp.asarray(prompt), cfg, max_new=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_generate_from_export(tmp_path):
    """`edl generate` — the one-command serving consumer: rebuilds the
    config from the manifest's architecture record and decodes."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3,4", "--max-new", "5",
        ],
        capture_output=True,
        text=True,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        },
    )
    assert out.returncode == 0, out.stderr
    toks = [int(t) for t in out.stdout.strip().split(",")]
    want = llama.generate(
        params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), cfg, max_new=5
    )
    assert toks == [int(t) for t in np.asarray(want)[0]]
    # an export without an architecture record is a clear error
    export_params(str(tmp_path / "bare"), params, step=1, dtype="float32")
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate",
            str(tmp_path / "bare"), "--prompt", "1",
        ],
        capture_output=True,
        text=True,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        },
    )
    assert out.returncode == 1 and "architecture record" in out.stderr


def test_config_meta_roundtrip():
    cfg = llama.LlamaConfig.tiny()
    back = llama.LlamaConfig.from_meta(cfg.to_meta())
    assert back.d_model == cfg.d_model and back.n_kv_heads == cfg.n_kv_heads
    import json

    json.dumps(cfg.to_meta())  # JSON-safe
    with pytest.raises(ValueError, match="not a llama export"):
        llama.LlamaConfig.from_meta({"family": "bert"})


def test_generate_sampling_shape_and_determinism():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.zeros((3, 4), np.int32)
    key = jax.random.PRNGKey(7)
    a = llama.generate(
        params, jnp.asarray(prompt), cfg, max_new=4, temperature=0.8, key=key
    )
    b = llama.generate(
        params, jnp.asarray(prompt), cfg, max_new=4, temperature=0.8, key=key
    )
    assert a.shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) < cfg.vocab).all() and (np.asarray(a) >= 0).all()
    with pytest.raises(ValueError, match="PRNG key"):
        llama.generate(params, jnp.asarray(prompt), cfg, 2, temperature=0.5)


def test_sharded_generate_matches_single_device(tmp_path, cpu_devices):
    """Sharded serving (VERDICT r3 #3): the export loads directly onto
    a tp×fsdp mesh via load_export_sharded — each device holds only its
    shard of every weight (the path for exports bigger than one chip's
    HBM) — and generate produces token-identical output."""
    from jax.sharding import PartitionSpec as P

    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime.export import load_export_sharded

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    export_params(
        str(tmp_path), params, step=7, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    plan = MeshPlan.parse("tp=2,fsdp=2,dp", 8)
    mesh = plan.build()
    loaded, doc = load_export_sharded(
        str(tmp_path), mesh, llama.param_pspecs(cfg, plan)
    )
    assert doc["step"] == 7
    # really sharded at rest: wq holds 1/4 per device (fsdp x tp)
    wq = loaded["layers"]["wq"]
    assert wq.sharding.spec == P(None, "fsdp", "tp")
    assert {s.data.shape for s in wq.addressable_shards} == {
        (cfg.n_layers, cfg.d_model // 2, cfg.n_heads * cfg.head_dim // 2)
    }
    prompt = np.arange(2 * 6, dtype=np.int32).reshape(2, 6) % cfg.vocab
    got = llama.generate(loaded, jnp.asarray(prompt), cfg, max_new=6)
    want = llama.generate(params, jnp.asarray(prompt), cfg, max_new=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_generate_sharded_mesh(tmp_path):
    """`edl generate --mesh tp=2` serves the export sharded over a
    virtual device mesh and produces the same tokens as single-device."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3,4", "--max-new", "5", "--mesh", "tp=2",
        ],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    toks = [int(t) for t in out.stdout.strip().split(",")]
    want = llama.generate(
        params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), cfg, max_new=5
    )
    assert toks == [int(t) for t in np.asarray(want)[0]]


# -- weight-only int8 decode (the serving quantization lever) ---------------


def _dequant_dense(qp):
    """Fold every {"q8","s8"} record back to a dense f32 matrix — the
    math `_matw` must be exactly equivalent to (modulo one float
    reassociation)."""

    def fold(node):
        if isinstance(node, dict):
            if set(node) == {"q8", "s8"}:
                return node["q8"].astype(jnp.float32) * node["s8"][
                    ..., None, :
                ].astype(jnp.float32)
            return {k: fold(v) for k, v in node.items()}
        return node

    return fold(qp)


def test_int8_quantize_structure_and_error_bound():
    """Symmetric per-output-column absmax: every matmul weight becomes
    an int8 record whose reconstruction error is <= colmax/254 per
    element; embedding and norm scales stay dense."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(3), cfg)
    qp = llama.quantize_params_int8(params)

    assert not isinstance(qp["embed"], dict)
    assert not isinstance(qp["layers"]["ln1"], dict)
    assert not isinstance(qp["ln_f"], dict)
    for name in ("wq", "wk", "wv", "wo", "w1", "w2", "w3"):
        rec = qp["layers"][name]
        assert set(rec) == {"q8", "s8"}, name
        assert rec["q8"].dtype == jnp.int8
        w = np.asarray(params["layers"][name])
        r = np.asarray(rec["q8"], np.float32) * np.asarray(rec["s8"])[
            ..., None, :
        ]
        colmax = np.abs(w).max(axis=-2)
        assert (np.abs(w - r).max(axis=-2) <= colmax / 254 + 1e-7).all(), name
    assert set(qp["lm_head"]) == {"q8", "s8"}


def test_int8_forward_matches_dequantized_oracle():
    """`_matw`'s (a @ q8) * s8 must equal a @ (q8 * s8) — the int8
    record is a lossless re-association of the dequantized matmul, so
    forward logits through the record match a dense fold of it."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(4), cfg)
    qp = llama.quantize_params_int8(params)
    dense = _dequant_dense(qp)
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab, (2, 12), np.int32)
    )
    got = np.asarray(llama.forward(qp, toks, cfg))
    want = np.asarray(llama.forward(dense, toks, cfg))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_int8_generate_fidelity():
    """Greedy decode through the int8 records: identical tokens to the
    dequantized-dense oracle, and logits within quantization noise of
    the full-precision model."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(5), cfg)
    qp = llama.quantize_params_int8(params)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab, (2, 8), np.int32)
    )
    got = np.asarray(llama.generate(qp, prompt, cfg, max_new=6))
    want = np.asarray(
        llama.generate(_dequant_dense(qp), prompt, cfg, max_new=6)
    )
    np.testing.assert_array_equal(got, want)

    l_full = np.asarray(llama.forward(params, prompt, cfg))
    l_q = np.asarray(llama.forward(qp, prompt, cfg))
    assert np.abs(l_full - l_q).max() < 0.3 * l_full.std()


def test_cli_generate_int8(tmp_path):
    """`edl generate --int8` serves the export through the weight-only
    int8 records; on the tiny model greedy tokens match full precision."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3,4", "--max-new", "5", "--int8",
        ],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    toks = [int(t) for t in out.stdout.strip().split(",")]
    qp = llama.quantize_params_int8(params)
    want = llama.generate(
        qp, jnp.asarray([[1, 2, 3, 4]], jnp.int32), cfg, max_new=5
    )
    assert toks == [int(t) for t in np.asarray(want)[0]]

    both = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2", "--max-new", "2", "--int8", "--mesh", "tp=2",
        ],
        capture_output=True, text=True, env=env,
    )
    assert both.returncode == 1
    assert "mutually exclusive" in both.stderr


# -- top-k / top-p sampling controls ----------------------------------------


def test_top_k_one_equals_greedy():
    """top_k=1 collapses sampling to greedy regardless of key/temp."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[3, 5, 7, 9]], jnp.int32)
    greedy = llama.generate(params, prompt, cfg, max_new=6)
    for seed in (0, 7):
        sampled = llama.generate(
            params, prompt, cfg, max_new=6, temperature=1.5,
            key=jax.random.PRNGKey(seed), top_k=1,
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_tiny_top_p_equals_greedy():
    """top_p -> 0 keeps only the most likely token (the exclusive-
    cumsum construction never empties the support)."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    prompt = jnp.asarray([[2, 4, 6]], jnp.int32)
    greedy = llama.generate(params, prompt, cfg, max_new=5)
    nucleus = llama.generate(
        params, prompt, cfg, max_new=5, temperature=1.0,
        key=jax.random.PRNGKey(3), top_p=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))


def test_top_k_restricts_support():
    """Every sampled token must come from the step's top-k logits:
    verified by replaying the sampled prefix through forward and
    checking membership at each position."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    prompt = np.asarray([[1, 2, 3, 4]], np.int32)
    k = 5
    toks = np.asarray(
        llama.generate(
            params, jnp.asarray(prompt), cfg, max_new=6, temperature=2.0,
            key=jax.random.PRNGKey(9), top_k=k,
        )
    )
    seq = prompt
    for t in range(toks.shape[1]):
        logits = np.asarray(llama.forward(params, jnp.asarray(seq), cfg))
        topk_ids = np.argsort(logits[0, -1])[::-1][:k]
        assert toks[0, t] in topk_ids, (t, toks[0, t], topk_ids)
        seq = np.concatenate([seq, toks[:, t : t + 1]], axis=1)


def test_cli_generate_top_flags(tmp_path):
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3", "--max-new", "4", "--temperature", "0.9",
            "--top-k", "8", "--top-p", "0.9",
        ],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert len(out.stdout.strip().split(",")) == 4

    bad = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2", "--max-new", "2", "--top-p", "1.5",
        ],
        capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 1
    assert "top_p" in bad.stderr


def test_cli_top_flags_require_temperature(tmp_path):
    """Greedy decoding ignores the sampling filters — the CLI errors
    instead of silently printing greedy tokens."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2", "--max-new", "2", "--top-k", "5",
        ],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 1
    assert "--temperature > 0" in out.stderr


def test_int8_records_compose_with_sampling_controls():
    """The two serving features compose: weight-only int8 records +
    top-k/top-p sampling in one generate call (the `edl generate
    --int8 --top-k ...` path). top_k=1 through the records must equal
    int8 greedy."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(6), cfg)
    qp = llama.quantize_params_int8(params)
    prompt = jnp.asarray([[4, 8, 15]], jnp.int32)

    greedy_q = llama.generate(qp, prompt, cfg, max_new=5)
    pick1 = llama.generate(
        qp, prompt, cfg, max_new=5, temperature=1.3,
        key=jax.random.PRNGKey(2), top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(pick1), np.asarray(greedy_q))

    sampled = llama.generate(
        qp, prompt, cfg, max_new=5, temperature=0.9,
        key=jax.random.PRNGKey(2), top_k=8, top_p=0.9,
    )
    assert sampled.shape == (1, 5)
    assert ((np.asarray(sampled) >= 0) & (np.asarray(sampled) < cfg.vocab)).all()
