"""KV-cache decode (llama.generate) — the serving half of the export
story. Oracle: iterative full-forward greedy decoding must produce the
same tokens as the cached scan."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.runtime.export import export_params, load_export


def _oracle_greedy(params, tokens, cfg, max_new):
    toks = jnp.asarray(tokens)
    out = []
    for _ in range(max_new):
        logits = llama.forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_generate_matches_full_forward_oracle():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab
    got = llama.generate(params, jnp.asarray(prompt), cfg, max_new=6)
    want = _oracle_greedy(params, prompt, cfg, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_from_export(tmp_path):
    """A fresh consumer: load the published export, generate — no
    TrainState, optimizer, or mesh."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    export_params(str(tmp_path), params, step=3, dtype="float32")
    loaded, _ = load_export(str(tmp_path))
    prompt = np.ones((1, 4), np.int32)
    got = llama.generate(loaded, jnp.asarray(prompt), cfg, max_new=5)
    want = llama.generate(params, jnp.asarray(prompt), cfg, max_new=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_generate_from_export(tmp_path):
    """`edl generate` — the one-command serving consumer: rebuilds the
    config from the manifest's architecture record and decodes."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3,4", "--max-new", "5",
        ],
        capture_output=True,
        text=True,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        },
    )
    assert out.returncode == 0, out.stderr
    toks = [int(t) for t in out.stdout.strip().split(",")]
    want = llama.generate(
        params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), cfg, max_new=5
    )
    assert toks == [int(t) for t in np.asarray(want)[0]]
    # an export without an architecture record is a clear error
    export_params(str(tmp_path / "bare"), params, step=1, dtype="float32")
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate",
            str(tmp_path / "bare"), "--prompt", "1",
        ],
        capture_output=True,
        text=True,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        },
    )
    assert out.returncode == 1 and "architecture record" in out.stderr


def test_config_meta_roundtrip():
    cfg = llama.LlamaConfig.tiny()
    back = llama.LlamaConfig.from_meta(cfg.to_meta())
    assert back.d_model == cfg.d_model and back.n_kv_heads == cfg.n_kv_heads
    import json

    json.dumps(cfg.to_meta())  # JSON-safe
    with pytest.raises(ValueError, match="not a llama export"):
        llama.LlamaConfig.from_meta({"family": "bert"})


def test_generate_sampling_shape_and_determinism():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = np.zeros((3, 4), np.int32)
    key = jax.random.PRNGKey(7)
    a = llama.generate(
        params, jnp.asarray(prompt), cfg, max_new=4, temperature=0.8, key=key
    )
    b = llama.generate(
        params, jnp.asarray(prompt), cfg, max_new=4, temperature=0.8, key=key
    )
    assert a.shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) < cfg.vocab).all() and (np.asarray(a) >= 0).all()
    with pytest.raises(ValueError, match="PRNG key"):
        llama.generate(params, jnp.asarray(prompt), cfg, 2, temperature=0.5)


def test_sharded_generate_matches_single_device(tmp_path, cpu_devices):
    """Sharded serving (VERDICT r3 #3): the export loads directly onto
    a tp×fsdp mesh via load_export_sharded — each device holds only its
    shard of every weight (the path for exports bigger than one chip's
    HBM) — and generate produces token-identical output."""
    from jax.sharding import PartitionSpec as P

    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.runtime.export import load_export_sharded

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    export_params(
        str(tmp_path), params, step=7, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    plan = MeshPlan.parse("tp=2,fsdp=2,dp", 8)
    mesh = plan.build()
    loaded, doc = load_export_sharded(
        str(tmp_path), mesh, llama.param_pspecs(cfg, plan)
    )
    assert doc["step"] == 7
    # really sharded at rest: wq holds 1/4 per device (fsdp x tp)
    wq = loaded["layers"]["wq"]
    assert wq.sharding.spec == P(None, "fsdp", "tp")
    assert {s.data.shape for s in wq.addressable_shards} == {
        (cfg.n_layers, cfg.d_model // 2, cfg.n_heads * cfg.head_dim // 2)
    }
    prompt = np.arange(2 * 6, dtype=np.int32).reshape(2, 6) % cfg.vocab
    got = llama.generate(loaded, jnp.asarray(prompt), cfg, max_new=6)
    want = llama.generate(params, jnp.asarray(prompt), cfg, max_new=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_generate_sharded_mesh(tmp_path):
    """`edl generate --mesh tp=2` serves the export sharded over a
    virtual device mesh and produces the same tokens as single-device."""
    import os
    import subprocess
    import sys

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(
        str(tmp_path), params, step=1, dtype="float32",
        model_meta=cfg.to_meta(),
    )
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    out = subprocess.run(
        [
            sys.executable, "-m", "edl_tpu.cli", "generate", str(tmp_path),
            "--prompt", "1,2,3,4", "--max-new", "5", "--mesh", "tp=2",
        ],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    toks = [int(t) for t in out.stdout.strip().split(",")]
    want = llama.generate(
        params, jnp.asarray([[1, 2, 3, 4]], jnp.int32), cfg, max_new=5
    )
    assert toks == [int(t) for t in np.asarray(want)[0]]
