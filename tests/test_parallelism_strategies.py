"""Ring attention (sp), pipeline (pp), MoE (ep): correctness against
unsharded oracles on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.parallel import moe
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.parallel.pipeline import pipeline_apply
from edl_tpu.parallel.ring_attention import reference_attention, ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(cpu_devices, causal):
    plan = MeshPlan.create(sp=4)
    mesh = plan.build(cpu_devices[:4])
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_with_dp_axis(cpu_devices):
    # sp composes with a data axis: batch sharded dp, seq sharded sp
    plan = MeshPlan.create(dp=2, sp=4)
    mesh = plan.build()
    rng = np.random.RandomState(1)
    b, t, h, d = 4, 16, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    sh = NamedSharding(mesh, P("dp", "sp", None, None))
    out = ring_attention(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh), mesh
    )
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pipeline_matches_sequential(cpu_devices):
    plan = MeshPlan.create(pp=4)
    mesh = plan.build(cpu_devices[:4])
    rng = np.random.RandomState(0)
    n_stages, d = 4, 16
    ws = jnp.asarray(rng.randn(n_stages, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(n_stages, d).astype(np.float32) * 0.1)
    params = {"w": ws, "b": bs}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    n_micro, mb = 8, 4
    x = jnp.asarray(rng.randn(n_micro, mb, d).astype(np.float32))
    out = pipeline_apply(stage_fn, params, x, mesh)
    # oracle: run all stages sequentially
    ref = x
    for s in range(n_stages):
        ref = jnp.tanh(ref @ ws[s] + bs[s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_flow(cpu_devices):
    # pipeline must be differentiable end to end (training usability)
    plan = MeshPlan.create(pp=2)
    mesh = plan.build(cpu_devices[:2])
    rng = np.random.RandomState(0)
    d = 8
    params = {
        "w": jnp.asarray(rng.randn(2, d, d).astype(np.float32) * 0.3),
        "b": jnp.zeros((2, d), jnp.float32),
    }
    x = jnp.asarray(rng.randn(4, 2, d).astype(np.float32))

    def loss(p):
        y = pipeline_apply(lambda pp, xx: jnp.tanh(xx @ pp["w"] + pp["b"]), p, x, mesh)
        return jnp.mean(y**2)

    g = jax.grad(loss)(params)
    assert float(jnp.sum(jnp.abs(g["w"]))) > 0
    assert np.isfinite(float(jnp.sum(g["w"])))


def test_moe_routes_and_balances():
    key = jax.random.PRNGKey(0)
    d, ff, e = 16, 32, 4
    params = moe.init_moe_params(key, d, ff, e)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 24, d))
    y, aux = moe.moe_ffn(params, x, k=2, capacity_factor=2.0)
    assert y.shape == x.shape
    assert np.isfinite(float(aux))
    # with generous capacity every token is processed: output nonzero
    assert float(jnp.mean(jnp.abs(y))) > 1e-4


def test_moe_matches_dense_when_one_expert():
    # n_experts=1, k=1: MoE must equal the plain FFN it degenerates to
    key = jax.random.PRNGKey(2)
    d, ff = 8, 16
    params = moe.init_moe_params(key, d, ff, 1)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, d))
    y, _ = moe.moe_ffn(params, x, k=1, capacity_factor=1.0)
    ref = jax.nn.relu(x @ params["w_in"][0]) @ params["w_out"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_moe_sharded_over_ep(cpu_devices):
    # expert dim sharded over ep in a jit: result identical to unsharded
    plan = MeshPlan.create(dp=2, ep=4)
    mesh = plan.build()
    d, ff, e = 16, 32, 4
    params = moe.init_moe_params(jax.random.PRNGKey(0), d, ff, e)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, d))
    specs = moe.moe_pspecs(plan)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, None)))
    f = jax.jit(lambda p, xx: moe.moe_ffn(p, xx, k=2, capacity_factor=2.0)[0])
    y_sharded = f(sharded, xs)
    y_ref = f(params, x)
    np.testing.assert_allclose(
        np.asarray(y_sharded), np.asarray(y_ref), atol=2e-5
    )


# ---------------------------------------------------------------------------
# Ulysses sequence parallelism (all-to-all)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_reference(cpu_devices, causal):
    from edl_tpu.parallel.ulysses import ulysses_attention

    plan = MeshPlan.create(sp=4)
    mesh = plan.build(cpu_devices[:4])
    rng = np.random.RandomState(2)
    b, t, h, d = 2, 32, 8, 16  # h divisible by sp
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_with_dp_axis(cpu_devices):
    from edl_tpu.parallel.ulysses import ulysses_attention

    plan = MeshPlan.create(dp=2, sp=4)
    mesh = plan.build()
    rng = np.random.RandomState(3)
    b, t, h, d = 4, 16, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    sh = NamedSharding(mesh, P("dp", "sp", None, None))
    out = ulysses_attention(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh), mesh
    )
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_indivisible_heads(cpu_devices):
    from edl_tpu.parallel.ulysses import ulysses_attention

    plan = MeshPlan.create(sp=4)
    mesh = plan.build(cpu_devices[:4])
    x = jnp.zeros((1, 8, 6, 4))  # 6 heads, sp=4
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(x, x, x, mesh)
