"""CLI + job store + monitor tests.

Covers the daemon/CLI surface the reference exercises by hand through
kubectl + collector.py (reference: doc/usage.md walkthrough;
example/fit_a_line/collector.py): submit → controller daemon ticks →
status/list/monitor observe the job running, scaled by the autoscaler;
delete drains it.
"""

import io
import json
import os

import pytest

from edl_tpu.api.job import TrainingJob
from edl_tpu.cli.main import main
from edl_tpu.cli.store import JobStore
from edl_tpu.monitor.collector import ClusterSource, Collector, StoreSource

ELASTIC_YAML = """
metadata:
  name: {name}
  namespace: default
spec:
  fault_tolerant: true
  passes: 1
  worker:
    entrypoint: "python train.py"
    min_replicas: 2
    max_replicas: 10
    resources:
      limits:
        cpu: "4"
        memory: 2Gi
        tpu: 4
"""


def _write_manifest(tmp_path, name="example"):
    p = tmp_path / f"{name}.yaml"
    p.write_text(ELASTIC_YAML.format(name=name))
    return str(p)


def test_job_dict_roundtrip():
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="rt"))
    again = TrainingJob.from_dict(job.to_dict())
    assert again.name == "rt"
    assert again.spec.worker.min_replicas == 2
    assert again.spec.worker.max_replicas == 10
    assert again.chips_per_worker() == 4
    assert again.spec.fault_tolerant
    assert again.spec.worker.entrypoint == "python train.py"
    assert again.to_dict() == job.to_dict()


def test_store_submit_list_delete(tmp_path):
    store = JobStore(str(tmp_path))
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="a"))
    store.submit(job)
    assert store.list_keys() == [("default", "a")]
    loaded = store.load("default", "a")
    assert loaded.spec.worker.max_replicas == 10
    assert store.delete("default", "a")
    assert store.list_keys() == []
    assert not store.delete("default", "a")


def test_validate_command(tmp_path, capsys):
    m = _write_manifest(tmp_path)
    assert main(["validate", m]) == 0
    out = capsys.readouterr().out
    assert "workers=2-10" in out and "elastic=True" in out


def test_validate_rejects_elastic_without_ft(tmp_path, capsys):
    p = tmp_path / "bad.yaml"
    p.write_text(
        """
metadata: {name: bad}
spec:
  worker: {min_replicas: 2, max_replicas: 4}
"""
    )
    assert main(["validate", str(p)]) == 1
    assert "INVALID" in capsys.readouterr().err


def test_submit_controller_status_flow(tmp_path, capsys):
    store_dir = str(tmp_path / "store")
    m = _write_manifest(tmp_path, "example")
    assert main(["submit", m, "--store", store_dir]) == 0

    # a few daemon ticks on a synthetic 4-host x 8-chip fleet
    assert (
        main(
            [
                "controller",
                "--store",
                store_dir,
                "--hosts",
                "4",
                "--chips-per-host",
                "8",
                "--tick-s",
                "0",
                "--iterations",
                "5",
            ]
        )
        == 0
    )

    store = JobStore(store_dir)
    st = store.read_status("default", "example")
    assert st is not None
    assert st["phase"] == "running"
    assert st["running"] >= 2  # at least min replicas placed
    # elastic: autoscaler grows the job toward max within chip capacity
    # (32 chips / 4 per worker = 8 workers)
    assert st["parallelism"] >= 2
    census = store.read_cluster()
    assert census["chip_total"] == 32

    capsys.readouterr()
    assert main(["list", "--store", store_dir]) == 0
    out = capsys.readouterr().out
    assert "example" in out and "running" in out

    assert main(["status", "example", "--store", store_dir]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["name"] == "example"

    assert main(["monitor", "--store", store_dir, "--polls", "1"]) == 0
    out = capsys.readouterr().out
    assert "SUBMITTED-JOBS: 1" in out
    assert "CHIP-UTILS" in out


def test_delete_drains_job(tmp_path):
    store_dir = str(tmp_path / "store")
    m = _write_manifest(tmp_path, "gone")
    assert main(["submit", m, "--store", store_dir]) == 0
    args = [
        "controller", "--store", store_dir, "--tick-s", "0", "--iterations", "3",
    ]
    assert main(args) == 0
    assert main(["delete", "gone", "--store", store_dir]) == 0
    assert main(args) == 0
    store = JobStore(store_dir)
    assert store.read_status("default", "gone") is None
    census = store.read_cluster()
    assert census["chip_request"] == 0


def test_controller_rejects_invalid_job(tmp_path):
    store_dir = str(tmp_path / "store")
    store = JobStore(store_dir)
    bad = TrainingJob.from_yaml(
        """
metadata: {name: bad}
spec:
  worker: {min_replicas: 2, max_replicas: 4}
"""
    )
    store.submit(bad)  # bypasses CLI admission, daemon must still reject
    assert (
        main(["controller", "--store", store_dir, "--tick-s", "0",
              "--iterations", "2"])
        == 0
    )
    st = store.read_status("default", "bad")
    assert st["phase"] == "failed"
    assert "validation" in st["reason"]


def test_monitor_cluster_source_pending_detection():
    from edl_tpu.cluster.fake import FakeCluster, FakeHost

    cluster = FakeCluster(hosts=[FakeHost(name="h0", cpu_milli=8000,
                                          mem_mega=16384, chips=8)])
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="mon"))
    from edl_tpu.api.parser import JobParser

    JobParser().validate(job)
    cluster.submit_job(job)
    # nothing reconciled yet -> no pods at all, so not "pending" either
    sample = ClusterSource(cluster).sample()
    assert sample.submitted_jobs == ["mon"]
    assert sample.chip_total == 8

    buf = io.StringIO()
    Collector(ClusterSource(cluster), interval_s=0, out=buf).run(n_polls=2)
    text = buf.getvalue()
    assert text.count("SUBMITTED-JOBS") == 2


def test_monitor_renders_host_fallbacks():
    """Slow-path (host-staged) reshards surface in the monitor output
    as an alarm signal (doc/reshard_stall.md)."""
    from edl_tpu.monitor.collector import MonitorSample

    s = MonitorSample(
        submitted_jobs=["j"],
        running_workers={"j": 2},
        reshards={"j": 3},
        last_stall_s={"j": 0.5},
        reshard_fallbacks={"j": 1},
    )
    out = s.render()
    assert "reshards=3" in out and "host_fallbacks=1" in out


def test_job_status_reads_live_coordinator(tmp_path, capsys):
    """`edl job-status` — the operator's one-command view into a
    running process-runtime job: live KV metrics + queue accounting
    from the job coordinator."""
    import pytest as _pytest

    from edl_tpu.runtime.coordinator import (
        CoordinatorServer,
        ensure_native_built,
    )

    if not ensure_native_built():
        _pytest.skip("no C++ toolchain")
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        c = srv.client()
        c.register("w000", 1)
        c.kv_put("myjob/progress", "17")
        c.kv_put("myjob/loss_first", "2.5")
        c.kv_put("myjob/loss_last", "0.9")
        c.kv_put("myjob/eval_metric", "16:0.87")
        c.kv_put("myjob/restore_last", "p2p:12")
        c.queue_init(128, 32, 1, 16.0)
        assert main(["job-status", "myjob",
                     "--coordinator", f"127.0.0.1:{srv.port}"]) == 0
        out = capsys.readouterr().out
        assert "progress" in out and "17" in out
        assert "eval_metric" in out and "16:0.87" in out
        assert "p2p:12" in out and "w000" in out
        assert "todo=4" in out
        c.close()
    # unreachable coordinator is a clean error, not a traceback
    assert main(["job-status", "x", "--coordinator", "127.0.0.1:1"]) == 1
    assert "cannot reach" in capsys.readouterr().err
