"""CLI + job store + monitor tests.

Covers the daemon/CLI surface the reference exercises by hand through
kubectl + collector.py (reference: doc/usage.md walkthrough;
example/fit_a_line/collector.py): submit → controller daemon ticks →
status/list/monitor observe the job running, scaled by the autoscaler;
delete drains it.
"""

import io
import json
import os

import pytest

from edl_tpu.api.job import TrainingJob
from edl_tpu.cli.main import main
from edl_tpu.cli.store import JobStore
from edl_tpu.monitor.collector import ClusterSource, Collector, StoreSource

ELASTIC_YAML = """
metadata:
  name: {name}
  namespace: default
spec:
  fault_tolerant: true
  passes: 1
  worker:
    entrypoint: "python train.py"
    min_replicas: 2
    max_replicas: 10
    resources:
      limits:
        cpu: "4"
        memory: 2Gi
        tpu: 4
"""


def _write_manifest(tmp_path, name="example"):
    p = tmp_path / f"{name}.yaml"
    p.write_text(ELASTIC_YAML.format(name=name))
    return str(p)


def test_job_dict_roundtrip():
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="rt"))
    again = TrainingJob.from_dict(job.to_dict())
    assert again.name == "rt"
    assert again.spec.worker.min_replicas == 2
    assert again.spec.worker.max_replicas == 10
    assert again.chips_per_worker() == 4
    assert again.spec.fault_tolerant
    assert again.spec.worker.entrypoint == "python train.py"
    assert again.to_dict() == job.to_dict()


def test_store_submit_list_delete(tmp_path):
    store = JobStore(str(tmp_path))
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="a"))
    store.submit(job)
    assert store.list_keys() == [("default", "a")]
    loaded = store.load("default", "a")
    assert loaded.spec.worker.max_replicas == 10
    assert store.delete("default", "a")
    assert store.list_keys() == []
    assert not store.delete("default", "a")


def test_validate_command(tmp_path, capsys):
    m = _write_manifest(tmp_path)
    assert main(["validate", m]) == 0
    out = capsys.readouterr().out
    assert "workers=2-10" in out and "elastic=True" in out


def test_profile_renders_bench_roofline(tmp_path, capsys):
    """`edl profile BENCH.json` — the offline roofline twin, fully
    device-free (no jax import on this path)."""
    doc = {
        "parsed": {
            "mfu": 0.53, "int8_mfu": 0.59, "peak_tflops": 197.0,
            "decode_ladder": [
                {"b": 1, "decode_pct_peak_bw": 0.93,
                 "decode_tokens_per_sec": 400.0},
                {"b": 8, "decode_pct_peak_bw": -1.0},  # sentinel: hidden
            ],
            "prefill_s": 0.17, "flagship_state_gb": 3.5,
            "compile_s": 2.9,
        }
    }
    p = tmp_path / "BENCH_rXX.json"
    p.write_text(json.dumps(doc))
    assert main(["profile", str(p)]) == 0
    out = capsys.readouterr().out
    assert "EDL ROOFLINE" in out and "train" in out
    assert "53.0%" in out and "93.0%" in out
    assert "decode_b8" not in out  # sentinel rung stays hidden
    # --json round-trips
    assert main(["profile", str(p), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["phases"]["train"]["mfu"] == 0.53
    assert rep["phases"]["decode_b1"]["bw_util"] == 0.93
    # bad sources exit 2 with a clean message
    assert main(["profile", "definitely-not-listening:1"]) == 2
    assert "cannot profile" in capsys.readouterr().err
    assert main(["profile"]) == 2


def test_validate_rejects_elastic_without_ft(tmp_path, capsys):
    p = tmp_path / "bad.yaml"
    p.write_text(
        """
metadata: {name: bad}
spec:
  worker: {min_replicas: 2, max_replicas: 4}
"""
    )
    assert main(["validate", str(p)]) == 1
    assert "INVALID" in capsys.readouterr().err


def test_submit_controller_status_flow(tmp_path, capsys):
    store_dir = str(tmp_path / "store")
    m = _write_manifest(tmp_path, "example")
    assert main(["submit", m, "--store", store_dir]) == 0

    # a few daemon ticks on a synthetic 4-host x 8-chip fleet
    assert (
        main(
            [
                "controller",
                "--store",
                store_dir,
                "--hosts",
                "4",
                "--chips-per-host",
                "8",
                "--tick-s",
                "0",
                "--iterations",
                "5",
            ]
        )
        == 0
    )

    store = JobStore(store_dir)
    st = store.read_status("default", "example")
    assert st is not None
    assert st["phase"] == "running"
    assert st["running"] >= 2  # at least min replicas placed
    # elastic: autoscaler grows the job toward max within chip capacity
    # (32 chips / 4 per worker = 8 workers)
    assert st["parallelism"] >= 2
    census = store.read_cluster()
    assert census["chip_total"] == 32

    capsys.readouterr()
    assert main(["list", "--store", store_dir]) == 0
    out = capsys.readouterr().out
    assert "example" in out and "running" in out

    assert main(["status", "example", "--store", store_dir]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["name"] == "example"

    assert main(["monitor", "--store", store_dir, "--polls", "1"]) == 0
    out = capsys.readouterr().out
    assert "SUBMITTED-JOBS: 1" in out
    assert "CHIP-UTILS" in out


def test_delete_drains_job(tmp_path):
    store_dir = str(tmp_path / "store")
    m = _write_manifest(tmp_path, "gone")
    assert main(["submit", m, "--store", store_dir]) == 0
    args = [
        "controller", "--store", store_dir, "--tick-s", "0", "--iterations", "3",
    ]
    assert main(args) == 0
    assert main(["delete", "gone", "--store", store_dir]) == 0
    assert main(args) == 0
    store = JobStore(store_dir)
    assert store.read_status("default", "gone") is None
    census = store.read_cluster()
    assert census["chip_request"] == 0


def test_controller_rejects_invalid_job(tmp_path):
    store_dir = str(tmp_path / "store")
    store = JobStore(store_dir)
    bad = TrainingJob.from_yaml(
        """
metadata: {name: bad}
spec:
  worker: {min_replicas: 2, max_replicas: 4}
"""
    )
    store.submit(bad)  # bypasses CLI admission, daemon must still reject
    assert (
        main(["controller", "--store", store_dir, "--tick-s", "0",
              "--iterations", "2"])
        == 0
    )
    st = store.read_status("default", "bad")
    assert st["phase"] == "failed"
    assert "validation" in st["reason"]


def test_monitor_cluster_source_pending_detection():
    from edl_tpu.cluster.fake import FakeCluster, FakeHost

    cluster = FakeCluster(hosts=[FakeHost(name="h0", cpu_milli=8000,
                                          mem_mega=16384, chips=8)])
    job = TrainingJob.from_yaml(ELASTIC_YAML.format(name="mon"))
    from edl_tpu.api.parser import JobParser

    JobParser().validate(job)
    cluster.submit_job(job)
    # nothing reconciled yet -> no pods at all, so not "pending" either
    sample = ClusterSource(cluster).sample()
    assert sample.submitted_jobs == ["mon"]
    assert sample.chip_total == 8

    buf = io.StringIO()
    Collector(ClusterSource(cluster), interval_s=0, out=buf).run(n_polls=2)
    text = buf.getvalue()
    assert text.count("SUBMITTED-JOBS") == 2


def test_monitor_renders_host_fallbacks():
    """Slow-path (host-staged) reshards surface in the monitor output
    as an alarm signal (doc/reshard_stall.md)."""
    from edl_tpu.monitor.collector import MonitorSample

    s = MonitorSample(
        submitted_jobs=["j"],
        running_workers={"j": 2},
        reshards={"j": 3},
        last_stall_s={"j": 0.5},
        reshard_fallbacks={"j": 1},
    )
    out = s.render()
    assert "reshards=3" in out and "host_fallbacks=1" in out


def test_job_status_reads_live_coordinator(tmp_path, capsys):
    """`edl job-status` — the operator's one-command view into a
    running process-runtime job: live KV metrics + queue accounting
    from the job coordinator."""
    import pytest as _pytest

    from edl_tpu.runtime.coordinator import (
        CoordinatorServer,
        ensure_native_built,
    )

    if not ensure_native_built():
        _pytest.skip("no C++ toolchain")
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        c = srv.client()
        c.register("w000", 1)
        c.kv_put("myjob/progress", "17")
        c.kv_put("myjob/loss_first", "2.5")
        c.kv_put("myjob/loss_last", "0.9")
        c.kv_put("myjob/eval_metric", "16:0.87")
        c.kv_put("myjob/restore_last", "p2p:12")
        c.queue_init(128, 32, 1, 16.0)
        assert main(["job-status", "myjob",
                     "--coordinator", f"127.0.0.1:{srv.port}"]) == 0
        out = capsys.readouterr().out
        assert "progress" in out and "17" in out
        assert "eval_metric" in out and "16:0.87" in out
        assert "p2p:12" in out and "w000" in out
        assert "todo=4" in out
        c.close()
    # unreachable coordinator is a clean error, not a traceback
    assert main(["job-status", "x", "--coordinator", "127.0.0.1:1"]) == 1
    assert "cannot reach" in capsys.readouterr().err


def test_monitor_json_emits_machine_readable_samples(tmp_path, capsys):
    """`edl monitor --json` — JSONL twin of the text table, tailable
    by scripts and the future autoscaler."""
    store_dir = str(tmp_path / "store")
    m = _write_manifest(tmp_path, "jm")
    assert main(["submit", m, "--store", store_dir]) == 0
    assert main(["controller", "--store", store_dir, "--tick-s", "0",
                 "--iterations", "3"]) == 0
    capsys.readouterr()
    assert main(["monitor", "--store", store_dir, "--polls", "2",
                 "--interval", "0", "--json"]) == 0
    lines = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.strip()
    ]
    assert len(lines) == 2
    for ln in lines:
        rec = json.loads(ln)  # one JSON object per poll, no table text
        assert rec["submitted_jobs"] == ["jm"]
        assert rec["chip_total"] == 32
        assert rec["running_workers"]["jm"] >= 2
        assert 0.0 <= rec["chip_util"] <= 100.0


def test_controller_fleet_exporter_scrapes_census():
    """`edl controller --metrics-port` — the scrapeable twin of the
    monitor: each /metrics GET samples the live cluster census."""
    from types import SimpleNamespace

    from edl_tpu import obs
    from edl_tpu.api.job import TrainingJob
    from edl_tpu.cli.main import _build_cluster, _start_fleet_exporter

    args = SimpleNamespace(
        hosts=2, chips_per_host=8, host_cpu_milli=96_000,
        host_mem_mega=393_216, metrics_port=0,
    )
    cluster = _build_cluster(args)
    cluster.submit_job(TrainingJob.from_yaml(ELASTIC_YAML.format(name="fx")))
    cluster.reconcile()
    exp = _start_fleet_exporter(args, cluster)
    try:
        fams = obs.parse_prometheus_text(obs.scrape(exp.url))
        assert fams["edl_fleet_chip_total"] == [({}, 16.0)]
        (labels, _), = fams["edl_job_parallelism"]
        assert labels == {"job": "fx"}
    finally:
        exp.stop()
    # metrics_port None -> no exporter
    args.metrics_port = None
    assert _start_fleet_exporter(args, cluster) is None


def test_edl_top_renders_one_screen_view(capsys):
    """`edl top ENDPOINT` — scrape + summarize the headline series."""
    from edl_tpu import obs

    reg = obs.MetricsRegistry()
    obs.ensure_core_series(reg)
    reg.get("edl_serving_tokens_total").inc(120)
    reg.get("edl_serving_ttft_seconds").observe(0.03)
    reg.get("edl_serving_queue_depth").set(3)
    reg.get("edl_serving_dispatch_total").inc(20, kind="decode")
    reg.get("edl_train_steps_total").inc(7)
    reg.get("edl_train_step_seconds").observe(0.02)
    reg.get("edl_reshard_total").inc(2, path="device")
    reg.get("edl_reshard_stall_seconds").observe(1.5)
    exp = obs.start_exporter(reg, port=0)
    endpoint = f"127.0.0.1:{exp.port}"
    try:
        assert main(["top", endpoint, "--polls", "1"]) == 0
        out = capsys.readouterr().out
        assert "EDL TOP" in out
        assert "SERVING" in out and "tokens=120" in out and "queue=3" in out
        assert "TRAIN" in out and "steps=7" in out
        assert "RESHARD" in out and "count=2" in out
    finally:
        exp.stop()
    # dead endpoint: clean error, not a traceback
    assert main(["top", endpoint, "--polls", "1", "--timeout", "0.5"]) == 1
    assert "scrape failed" in capsys.readouterr().err
