"""Delayed-sync (local SGD) data parallelism — the TPU translation of
the reference's relaxed-consistency pserver mode (``--async_mode``,
reference example/ctr/ctr/train.py:75-79).

Covers: exact equivalence with synchronous DP at K=1 under SGD,
convergence parity at K=4 on the CTR workload (the VERDICT acceptance
bar), elastic reshard mid-run under delayed sync, checkpointing the
consensus state, and the dp-only restriction.
"""

import numpy as np
import optax
import pytest


import jax
import jax.numpy as jnp

from edl_tpu.api.job import MeshSpec
from edl_tpu.models import ctr
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.parallel import sharding as shd
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.train.trainer import (
    LocalSyncStepper,
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def _ctr_setup(plan, vocab=1024, lr=1e-2, opt="adam"):
    mesh = plan.build()
    params = ctr.init_params(jax.random.PRNGKey(1), vocab=vocab, emb=8)
    tx = optax.sgd(lr) if opt == "sgd" else optax.adam(lr)
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    return mesh, tx, state


def test_k1_sgd_matches_sync_dp(cpu_devices):
    """One local SGD step then a group average IS the synchronous DP
    update (linearity of the SGD rule): p - lr*mean_i(g_i)."""
    plan = MeshPlan.data_parallel(4)
    mesh, tx, state0 = _ctr_setup(plan, opt="sgd")

    rng = np.random.RandomState(0)
    batches = [ctr.synthetic_batch(rng, 64, vocab=1024) for _ in range(4)]

    sync_step = make_train_step(ctr.loss_fn, tx, plan, mesh, donate=False)
    s_sync = state0
    for b in batches:
        s_sync, _ = sync_step(s_sync, global_batch(b, plan, mesh))

    stepper = LocalSyncStepper(ctr.loss_fn, tx, plan, mesh)
    s_loc = stepper.localize(state0)
    for b in batches:
        s_loc, _ = stepper.step(s_loc, global_batch(b, plan, mesh))
        s_loc = stepper.sync(s_loc)  # K=1: average after every step
    s_loc = stepper.merge(s_loc)

    a = shd.to_host(s_sync.params)
    b_ = shd.to_host(s_loc.params)
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b_)):
        np.testing.assert_allclose(x, y, rtol=2e-5, atol=1e-6)
    assert int(np.asarray(s_loc.step)) == 4


def test_convergence_parity_k4_ctr(cpu_devices):
    """K=4 delayed sync trains CTR to parity with synchronous DP —
    the VERDICT #8 acceptance criterion."""

    def run(sync_every):
        tr = ElasticTrainer(
            ctr.loss_fn,
            optax.adam(1e-2),
            mesh_spec=MeshSpec(dp=4),
            per_chip_batch=64,
            sync_every=sync_every,
        )
        tr.pool = tr.pool[:4]
        tr.start(ctr.init_params(jax.random.PRNGKey(2), vocab=2048, emb=8), 4)
        rng = np.random.RandomState(3)
        rep = tr.train_steps(
            lambda bs: ctr.synthetic_batch(rng, bs, vocab=2048), 96
        )
        return rep.losses

    sync_losses = run(1)
    local_losses = run(4)
    # both learn: final-quarter mean loss well below the start
    s_end = np.mean(sync_losses[-12:])
    l_end = np.mean(local_losses[-12:])
    assert s_end < sync_losses[0] * 0.8
    assert l_end < local_losses[0] * 0.8
    # parity: delayed sync within 15% of the synchronous endpoint
    assert l_end < s_end * 1.15, (s_end, l_end)


def test_reshard_and_checkpoint_under_delayed_sync(cpu_devices, tmp_path):
    """A rescale mid-round merges the groups, reshards, and re-forms
    them on the new dp width; checkpoints hold the consensus average."""
    tr = ElasticTrainer(
        ctr.loss_fn,
        optax.adam(1e-2),
        mesh_spec=MeshSpec(),
        per_chip_batch=32,
        sync_every=3,
        checkpoint_dir=str(tmp_path),
        checkpoint_every_steps=5,
    )
    tr.start(ctr.init_params(jax.random.PRNGKey(0), vocab=512, emb=8), 2)
    rng = np.random.RandomState(1)
    data = lambda bs: ctr.synthetic_batch(rng, bs, vocab=512)

    tr.train_steps(data, 4)
    tr.request_rescale(8)
    rep = tr.train_steps(data, 8)

    assert [(e.from_workers, e.to_workers) for e in rep.reshards] == [(2, 8)]
    assert tr.n_workers == 8
    assert int(np.asarray(tr.state.step)) == 12
    # checkpoint written at step 5 or 10 contains a MERGED (replicated)
    # state: leaves carry model shapes, no leading group axis
    from edl_tpu.runtime import checkpoint as ckpt

    paths = sorted(tmp_path.iterdir())
    assert paths, "no checkpoint written"
    template = TrainState.create(
        ctr.init_params(jax.random.PRNGKey(0), vocab=512, emb=8),
        optax.adam(1e-2),
    )
    loaded = ckpt.load(str(paths[0]), template)
    emb_shape = np.asarray(
        jax.tree_util.tree_leaves(loaded.params)[0]
    ).shape
    host_template_shape = np.asarray(
        jax.tree_util.tree_leaves(template.params)[0]
    ).shape
    assert emb_shape == host_template_shape
    # loss decreased over the run
    assert np.mean(rep.losses[-3:]) < rep.losses[0]


def test_merged_state_property(cpu_devices):
    tr = ElasticTrainer(
        ctr.loss_fn,
        optax.adam(1e-2),
        mesh_spec=MeshSpec(),
        per_chip_batch=32,
        sync_every=2,
    )
    tr.start(ctr.init_params(jax.random.PRNGKey(0), vocab=256, emb=8), 4)
    rng = np.random.RandomState(1)
    tr.train_steps(lambda bs: ctr.synthetic_batch(rng, bs, vocab=256), 3)
    merged = tr.merged_state
    live_emb = tr.state.params["embedding"]
    merged_emb = merged.params["embedding"]
    # live state is grouped (leading dp axis), merged is model-shaped
    assert live_emb.ndim == merged_emb.ndim + 1
    assert live_emb.shape[1:] == merged_emb.shape


def test_stepper_rejects_param_sharded_mesh(cpu_devices):
    plan = MeshPlan.fsdp_only(4)
    mesh = plan.build()
    with pytest.raises(ValueError, match="dp-only"):
        LocalSyncStepper(ctr.loss_fn, optax.adam(1e-3), plan, mesh)


@pytest.mark.multiproc  # real worker subprocesses, live timing
def test_multiproc_delayed_sync_scale_up(tmp_path):
    """Delayed-sync DP through the REAL multi-process runtime
    (EDL_SYNC_EVERY): K=2 local steps between averages, scaled up
    mid-run. The rescale merges the groups (collective on the healthy
    mesh), reshards, and re-forms them at the new dp width."""
    from edl_tpu.runtime.launcher import ProcessJobLauncher

    with ProcessJobLauncher(
        job="mpsync",
        model="linreg",
        min_workers=1,
        max_workers=4,
        n_samples=4096,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        sync_every=2,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=120)
        launcher.scale_to(3)
        rcs = launcher.wait(timeout_s=240)
        assert all(rc == 0 for rc in rcs.values()), (
            rcs,
            {w: launcher.log_tail(w, 4000) for w in rcs},
        )
        assert launcher.kv("phase") == "succeeded"
        assert int(launcher.kv("reshards") or "0") >= 1
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
