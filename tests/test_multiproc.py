"""Multi-process elastic training e2e — real worker processes.

The process-level analog of the reference's manual elastic demo
(reference: doc/boss_tutorial.md — jobs scaled while running, trainers
killed, job finishes anyway): workers are separate OS processes on a
virtual-CPU JAX backend with gloo cross-process collectives, membership
and data dispatch ride the native coordinator, and membership change is
an in-place ``jax.distributed`` re-init — the processes themselves
never restart (BASELINE north star: zero job restarts).

These tests do NOT use the in-process cpu_devices fixture — each worker
subprocess owns its own JAX runtime.
"""

import os
import time

import numpy as np
import pytest

# real worker subprocesses + live timing: run serially
# (scripts/run_tests.sh); CPU contention flakes these in-suite
pytestmark = pytest.mark.multiproc

from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.runtime.launcher import ProcessJobLauncher


def _assert_succeeded(launcher, rcs):
    assert all(rc == 0 for rc in rcs.values()), (
        rcs,
        {w: launcher.log_tail(w) for w in rcs},
    )
    assert launcher.kv("phase") == "succeeded"


def test_two_workers_train_and_complete(tmp_path):
    with ProcessJobLauncher(
        job="mp2",
        model="linreg",
        min_workers=2,
        max_workers=4,
        n_samples=1024,
        passes=1,
        per_device_batch=32,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        rcs = launcher.wait(timeout_s=180)
        _assert_succeeded(launcher, rcs)
        first = float(launcher.kv("loss_first"))
        last = float(launcher.kv("loss_last"))
        assert last < first, (first, last)
        # final committed sharded checkpoint carries the final step
        manifest = ckpt.latest_manifest(launcher.ckpt_dir)
        assert manifest is not None
        assert manifest["step"] == launcher.progress()
        assert int(launcher.kv("ckpt_step")) == launcher.progress()


def test_scale_up_reshards_in_place(tmp_path):
    with ProcessJobLauncher(
        job="mpup",
        model="linreg",
        min_workers=1,
        max_workers=4,
        n_samples=8192,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(3, timeout_s=120)
        launcher.scale_to(2)
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 2
        assert int(launcher.kv("reshards") or "0") >= 1
        # the original worker process survived the reshard in place (no
        # restart): ONE process's log shows more than one epoch bring-up
        log0 = launcher.log_tail("w000", n_bytes=100_000)
        assert log0.count("epoch up") >= 2, log0
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_scale_down_graceful_drain(tmp_path):
    with ProcessJobLauncher(
        job="mpdown",
        model="linreg",
        min_workers=3,
        max_workers=4,
        n_samples=8192,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(3)
        launcher.wait_progress(3, timeout_s=120)
        launcher.scale_to(2)  # SIGTERM the newest worker: graceful drain
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)  # including the drained worker
        assert int(launcher.kv("reshards") or "0") >= 1


def test_crash_sigkill_survivors_recover(tmp_path):
    """Hard-kill (no drain, no termination log): survivors recover from
    the last completed step via member-TTL expiry + collective failure
    (reference analog: pod deleted mid-job, master requeues its tasks)."""
    with ProcessJobLauncher(
        job="mpkill",
        model="linreg",
        min_workers=2,
        max_workers=4,
        n_samples=8192,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        member_ttl_s=2.0,
        lease_timeout_s=3.0,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=120)
        victim = launcher.live_workers()[-1].worker_id
        launcher.kill(victim)
        rcs = launcher.wait(timeout_s=300)
        assert rcs.pop(victim) != 0
        assert all(rc == 0 for rc in rcs.values()), (
            rcs,
            {w: launcher.log_tail(w) for w in rcs},
        )
        assert launcher.kv("phase") == "succeeded"
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_llama_fsdp_scale_up_reshards_in_place(tmp_path):
    """The flagship path (BASELINE config #5, VERDICT r1 #1): Llama
    under multi-process FSDP, scaled UP mid-run. Params/opt state are
    sharded across processes — no single host can snapshot them — so
    the reshard rides shard-local snapshots + the sharded checkpoint."""
    with ProcessJobLauncher(
        job="mplu",
        model="llama",
        mesh="fsdp",
        min_workers=1,
        max_workers=4,
        n_samples=384,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        step_sleep_s=0.1,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(2, timeout_s=240)
        launcher.scale_to(2)  # fsdp 2 -> 4 devices across 2 processes
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 2
        assert int(launcher.kv("reshards") or "0") >= 1
        # the original worker resharded in place (no restart)
        log0 = launcher.log_tail("w000", n_bytes=200_000)
        assert log0.count("epoch up") >= 2, log0
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
        assert ckpt.latest_manifest(launcher.ckpt_dir)["step"] == launcher.progress()


def test_llama_fsdp_scale_down_graceful_drain(tmp_path):
    """Flagship scale-DOWN: the departing worker's primary shards move
    through the sharded checkpoint it participates in writing before it
    drains; survivors restore at the smaller world."""
    with ProcessJobLauncher(
        job="mpld",
        model="llama",
        mesh="fsdp",
        min_workers=3,
        max_workers=4,
        n_samples=384,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        step_sleep_s=0.1,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(3)
        launcher.wait_progress(2, timeout_s=240)
        launcher.scale_to(2)  # drain the newest worker: fsdp 6 -> 4
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)  # including the drained worker
        assert int(launcher.kv("reshards") or "0") >= 1
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_llama_fsdp_crash_sigkill_rank0_rolls_back_to_commit(tmp_path):
    """Flagship worst case: SIGKILL rank 0 under multi-process FSDP.
    The dead process takes its primary shards with it, so survivors
    must roll back to the last COMMITTED sharded checkpoint (cadence
    ckpt_every) and still finish the job."""
    with ProcessJobLauncher(
        job="mplk0",
        model="llama",
        mesh="fsdp",
        min_workers=2,
        max_workers=4,
        n_samples=384,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=2,
        member_ttl_s=2.0,
        lease_timeout_s=3.0,
        step_sleep_s=0.1,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=240)
        victim = launcher.live_workers()[0].worker_id  # first = rank 0
        launcher.kill(victim)
        rcs = launcher.wait(timeout_s=600)
        assert rcs.pop(victim) != 0
        assert all(rc == 0 for rc in rcs.values()), (
            rcs,
            {w: launcher.log_tail(w) for w in rcs},
        )
        assert launcher.kv("phase") == "succeeded"
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
        # survivor rolled back to a committed step, then advanced
        assert ckpt.latest_manifest(launcher.ckpt_dir) is not None


def test_coordinator_sigkill_restart_job_completes(tmp_path):
    """The coordination plane is no longer a fatal SPOF (VERDICT r2
    #2): SIGKILL the coordinator mid-job, restart it, and the job
    completes with EXACT task accounting — the WAL restores KV,
    membership, and queue state; worker clients reconnect with backoff
    (the etcd-durability analog, reference pkg/jobparser.go:167-184)."""
    with ProcessJobLauncher(
        job="mpcoord",
        model="linreg",
        min_workers=2,
        max_workers=4,
        n_samples=4096,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=120)
        launcher.kill_coordinator()
        time.sleep(1.0)  # workers hit the dead socket and enter backoff
        launcher.restart_coordinator()
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
        # exact accounting across the crash: every chunk acked exactly
        # once (done == n_samples / chunk; chunk = 32 rows x 2 workers
        # at queue init), nothing dead
        stats = launcher.client.queue_stats()
        assert stats["done"] == 4096 // 32, stats
        assert stats["dead"] == 0 and stats["todo"] == 0 and stats["leased"] == 0


def test_llama_sp_pinned_elastic_scale_up(tmp_path):
    """Sequence parallelism as a FIRST-CLASS elastic strategy (VERDICT
    r2 #1a): mesh "sp=2,dp" pins the ring-attention axis while dp
    absorbs membership change — scale 1→2 workers mid-run, sp stays 2,
    job completes with exact task accounting."""
    with ProcessJobLauncher(
        job="mpsp",
        model="llama",
        mesh="sp=2,dp",
        min_workers=1,
        max_workers=4,
        n_samples=384,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        step_sleep_s=0.1,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(2, timeout_s=240)
        launcher.scale_to(2)  # sp=2 pinned; dp 1 -> 2 across 4 devices
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 2
        assert int(launcher.kv("reshards") or "0") >= 1
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_llama_pp_pinned_elastic_scale_up(tmp_path):
    """Pipeline parallelism as a FIRST-CLASS elastic strategy (VERDICT
    r2 #1b): mesh "pp=2,dp" pins the GPipe stage axis while dp absorbs
    membership change."""
    with ProcessJobLauncher(
        job="mppp",
        model="llama",
        mesh="pp=2,dp",
        min_workers=1,
        max_workers=4,
        n_samples=384,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        step_sleep_s=0.1,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(2, timeout_s=240)
        launcher.scale_to(2)  # pp=2 pinned; dp 1 -> 2 across 4 devices
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 2
        assert int(launcher.kv("reshards") or "0") >= 1
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_llama_fsdp_job_publishes_servable_export(tmp_path):
    """The commit leader publishes a params-only bf16 export on the
    checkpoint cadence and at stop (VERDICT r2 #6; reference
    save_inference_model, example/ctr/ctr/train.py:169-180) — and this
    process (not a worker) loads it for forward-only eval."""
    import jax
    import ml_dtypes

    from edl_tpu.models import llama
    from edl_tpu.runtime.export import load_export

    with ProcessJobLauncher(
        job="mpexp",
        model="llama",
        mesh="fsdp",
        min_workers=2,
        max_workers=2,
        n_samples=256,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        export=True,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(2)
        rcs = launcher.wait(timeout_s=300)
        _assert_succeeded(launcher, rcs)
        params, doc = load_export(launcher.export_dir)
        assert doc["step"] == launcher.progress()
        assert doc["dtype"] == "bfloat16"
        assert params["embed"].dtype == np.dtype(ml_dtypes.bfloat16)
        # servable: forward-only eval on the exported params alone
        cfg = llama.LlamaConfig.tiny(vocab=512)
        toks = np.arange(2 * 32, dtype=np.int32).reshape(2, 32) % 512
        logits = llama.forward(
            jax.tree_util.tree_map(lambda x: x.astype(np.float32), params),
            np.asarray(toks),
            cfg,
        )
        assert np.isfinite(np.asarray(logits)).all()


def test_workers_train_from_on_disk_shards(tmp_path):
    """Real data through the process runtime: CTR rows pre-written as
    shard files (EDL_DATA_DIR), leased through the coordinator queue,
    and read off disk by every worker (reference: per-trainer shard
    download, example/ctr/ctr/train.py:222-227)."""
    import numpy as np

    from edl_tpu.models import ctr
    from edl_tpu.runtime.shards import FileShardSource, write_shards

    rng = np.random.RandomState(7)
    rows = ctr.synthetic_batch(rng, 2048, vocab=4096)
    data_dir = str(tmp_path / "ds")
    write_shards(data_dir, rows, shard_size=512)

    with ProcessJobLauncher(
        job="mpdata",
        model="ctr",
        min_workers=2,
        max_workers=2,
        n_samples=999999,  # ignored: the manifest wins
        passes=1,
        per_device_batch=32,
        data_dir=data_dir,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "4096"},
    ) as launcher:
        launcher.start(2)
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        # the queue was sized from the manifest (2048 rows), not the
        # env's bogus n_samples: one task = one worker's 32-row step
        # share, so 64 tasks over 2 workers = 32 steps. Task accounting
        # is exactly-once (done == 64); the STEP count may run slightly
        # past 32 if a first-step compile outlasted a lease and the
        # chunk was redelivered (at-least-once delivery).
        expected_steps = 2048 // (32 * 2)
        assert expected_steps <= launcher.progress() <= expected_steps + 2
        stats = launcher.client.queue_stats()
        assert stats["done"] == 2048 // 32, stats
        assert stats["todo"] == 0 and stats["leased"] == 0, stats
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_100m_param_fsdp_ckpt_written_at_4_resumed_at_2_and_8(tmp_path):
    """VERDICT r1 #2 done-criterion: a ≥100M-param FSDP state committed
    at world=4 resumes at world=2 AND world=8, with per-host I/O (and
    therefore RAM) bounded by local shard bytes — each rank file holds
    ~1/world of the state, not all of it. Model: CTR with a 1.6M×64
    embedding (102M params, ~1.2 GB of f32 state with Adam moments)."""
    big = dict(
        model="ctr",
        mesh="fsdp",
        n_samples=32,
        passes=1,
        per_device_batch=4,
        local_devices=1,
        extra_env={"EDL_VOCAB": "1600000", "EDL_EMB": "64"},
    )
    wd = str(tmp_path)
    with ProcessJobLauncher(
        job="big4", min_workers=4, max_workers=4, work_dir=wd, **big
    ) as l4:
        l4.start(4)
        rcs = l4.wait(timeout_s=600)
        _assert_succeeded(l4, rcs)
        m = ckpt.latest_manifest(l4.ckpt_dir)
        assert m is not None and len(m["files"]) == 4
        total = sum(
            os.path.getsize(os.path.join(m["_dir"], f)) for f in m["files"]
        )
        assert total > 4 * 100e6 * 1.2  # >100M params of f32 + moments on disk
        for f in m["files"]:
            sz = os.path.getsize(os.path.join(m["_dir"], f))
            # per-rank file bounded by ~1/world of the state (+small
            # replicated leaves on the leader's file)
            assert sz < total / 4 * 1.5, (f, sz, total)
        step4 = m["step"]

    for world, jobname in ((2, "big2"), (8, "big8")):
        with ProcessJobLauncher(
            job=jobname,
            min_workers=world,
            max_workers=world,
            work_dir=wd,  # same ckpt dir: resume from the world-4 commit
            **big,
        ) as ln:
            ln.start(world)
            rcs = ln.wait(timeout_s=900)
            _assert_succeeded(ln, rcs)
            m2 = ckpt.latest_manifest(ln.ckpt_dir)
            assert m2["step"] > step4  # continued, not restarted
            assert len(m2["files"]) == world
            step4 = m2["step"]


def test_crash_sigkill_rank0_survivors_recover(tmp_path):
    """Worst case: the dead worker is rank 0 — it hosted the JAX
    coordination service AND published the per-step go decisions.
    Survivors must notice it left membership (TTL reap), reshard without
    a disconnect RPC, and finish the job."""
    with ProcessJobLauncher(
        job="mpkill0",
        model="linreg",
        min_workers=2,
        max_workers=4,
        n_samples=8192,
        passes=1,
        per_device_batch=32,
        step_sleep_s=0.05,
        member_ttl_s=2.0,
        lease_timeout_s=3.0,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=120)
        victim = launcher.live_workers()[0].worker_id  # first = rank 0
        launcher.kill(victim)
        rcs = launcher.wait(timeout_s=300)
        assert rcs.pop(victim) != 0
        assert all(rc == 0 for rc in rcs.values()), (
            rcs,
            {w: launcher.log_tail(w) for w in rcs},
        )
        assert launcher.kv("phase") == "succeeded"
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_background_commits_under_rescale(tmp_path):
    """Periodic commits run on a writer thread behind the step loop
    (background=True for the "ckpt" verb). ckpt_every=1 keeps a commit
    in flight at every step; a mid-run scale-up must serialize behind
    the pending write (join) and the final manifest must carry the
    final step."""
    with ProcessJobLauncher(
        job="mpbg",
        model="linreg",
        min_workers=1,
        max_workers=3,
        n_samples=8192,
        passes=1,
        per_device_batch=32,
        # 0.1s/step x ~128 steps: the scale event lands well before the
        # queue drains even when worker boot is slow under full-suite
        # CPU contention (reshards==0 flake otherwise)
        step_sleep_s=0.1,
        ckpt_every=1,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(3, timeout_s=120)
        launcher.scale_to(3)
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        assert int(launcher.kv("reshards") or "0") >= 1
        manifest = ckpt.latest_manifest(launcher.ckpt_dir)
        assert manifest is not None
        assert manifest["step"] == launcher.progress()
        assert int(launcher.kv("ckpt_step")) == launcher.progress()
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_llama_fsdp_elastic_scale_across_slices(tmp_path):
    """Slice-aware elastic process runtime (VERDICT r3 #1 — the
    BASELINE north-star shape, v5e-4 -> v5e-64 crossing slice
    boundaries): 2 workers start on virtual slice 0, the job scales to
    4 workers spanning slices {0,1} THROUGH the elastic runtime. The
    post-scale mesh must come up slice-major — dp varies across slices
    (DCN-legal), the pinned fsdp blocks stay inside one slice's ICI —
    and the job completes with exact task accounting."""
    with ProcessJobLauncher(
        job="mpslice",
        model="llama",
        mesh="fsdp=2,dp",
        min_workers=2,
        max_workers=4,
        n_samples=768,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        ckpt_every=4,
        step_sleep_s=0.25,
        workers_per_slice=2,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(2)
        launcher.wait_progress(2, timeout_s=240)
        launcher.scale_to(4)  # w002/w003 land on slice 1
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 4
        assert int(launcher.kv("reshards") or "0") >= 1
        # the multi-slice epoch's mesh device order: slice-major, with
        # each fsdp block (2 devices) inside one slice — a straddling
        # layout would have raised in MeshPlan.build and failed the job
        order = (launcher.kv("mesh_slices") or "").split(",")
        assert order == ["0"] * 4 + ["1"] * 4, order
        # exact accounting: queue chunk fixed at init (2 workers, 4
        # devices, batch_shards=4 -> 32 rows/step over world 2 = 16)
        stats = launcher.client.queue_stats()
        assert stats["done"] == 768 // 16, stats
        assert stats["dead"] == 0 and stats["todo"] == 0
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_slice_major_reorder_interleaved(tmp_path):
    """A membership whose process order interleaves slices (w000->0,
    w001->1, w002->0, w003->1) must still build a slice-major mesh:
    MeshPlan.build reorders the global device list so inner axes never
    straddle a slice. This is the layout-correctness half of the
    multi-slice contract, independent of elasticity."""
    with ProcessJobLauncher(
        job="mpilv",
        model="linreg",
        mesh="fsdp=2,dp",
        min_workers=4,
        max_workers=4,
        n_samples=4096,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        slice_map={"w000": 0, "w001": 1, "w002": 0, "w003": 1},
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(4)
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        # device order p0,p1,p2,p3 -> slice-major p0,p2 | p1,p3
        order = (launcher.kv("mesh_slices") or "").split(",")
        assert order == ["0"] * 4 + ["1"] * 4, order
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_migration_to_disjoint_workers_via_p2p(tmp_path):
    """Full job migration (VERDICT r3 #5): the job moves to a DISJOINT
    worker set mid-run. Owner-changing fsdp shards travel worker-to-
    worker over the P2P shard servers during the drain window — the
    departing workers linger serving their RAM snapshots until the new
    world confirms restore — instead of round-tripping through shared
    storage. The restore decision is observable (restore_last), and the
    job completes on the new workers with exact accounting."""
    import signal as _signal

    with ProcessJobLauncher(
        job="mpmig",
        model="llama",
        mesh="fsdp",
        min_workers=2,
        max_workers=4,
        n_samples=768,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        step_sleep_s=0.25,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(2)  # w000, w001
        launcher.wait_progress(2, timeout_s=240)
        # migrate: two fresh workers join, both originals drain
        launcher.spawn()  # w002
        launcher.spawn()  # w003
        launcher.kill("w000", sig=_signal.SIGTERM)
        launcher.kill("w001", sig=_signal.SIGTERM)
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 4  # originals drained cleanly (exit 0)
        assert int(launcher.kv("reshards") or "0") >= 1
        # the post-migration restore came from peers, not disk
        assert (launcher.kv("restore_last") or "").startswith("p2p:"), (
            launcher.kv("restore_last")
        )
        stats = launcher.client.queue_stats()
        assert stats["done"] == 768 // 16, stats
        assert stats["dead"] == 0 and stats["todo"] == 0
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_migration_across_slices_via_p2p(tmp_path):
    """The full north-star composition: the job MIGRATES to a disjoint
    worker set that also lives on DIFFERENT (virtual) slices — original
    workers on slice 0, replacements spanning slices {1,2}. State moves
    worker-to-worker over the P2P shard plane across the drain window,
    and the post-migration mesh comes up slice-major with the pinned
    fsdp blocks inside one slice each."""
    import signal as _signal

    with ProcessJobLauncher(
        job="mpmigsl",
        model="llama",
        mesh="fsdp=2,dp",
        min_workers=2,
        max_workers=6,
        n_samples=768,
        passes=1,
        per_device_batch=8,
        local_devices=2,
        seq_len=32,
        step_sleep_s=0.25,
        workers_per_slice=2,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "512"},
    ) as launcher:
        launcher.start(2)  # w000, w001 -> slice 0
        launcher.wait_progress(2, timeout_s=240)
        for _ in range(4):  # w002..w005 -> slices 1 and 2
            launcher.spawn()
        launcher.kill("w000", sig=_signal.SIGTERM)
        launcher.kill("w001", sig=_signal.SIGTERM)
        rcs = launcher.wait(timeout_s=480)
        _assert_succeeded(launcher, rcs)
        assert len(rcs) == 6
        # restored from peers across the slice boundary
        assert (launcher.kv("restore_last") or "").startswith("p2p:"), (
            launcher.kv("restore_last")
        )
        # final mesh: 8 devices slice-major across slices {1, 2}, fsdp
        # blocks (one worker's 2 devices) intact inside a slice
        order = (launcher.kv("mesh_slices") or "").split(",")
        assert order == ["1"] * 4 + ["2"] * 4, order
        stats = launcher.client.queue_stats()
        assert stats["done"] == 768 // 16, stats
        assert stats["dead"] == 0 and stats["todo"] == 0
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))


def test_ctr_job_publishes_auc_eval_metric(tmp_path):
    """The CTR workload's in-job eval (the reference's AUC fetched in
    the train loop, example/ctr/ctr/train.py:161-167): with a held-out
    shard dir configured, the commit leader evaluates every published
    export and the final eval_metric is a real AUC in (0, 1]."""
    import numpy as np

    from edl_tpu.models import ctr as ctr_model
    from edl_tpu.runtime import shards

    rng = np.random.RandomState(7)
    eval_rows = ctr_model.synthetic_batch(rng, 512, vocab=1024)
    eval_dir = str(tmp_path / "eval")
    shards.write_shards(eval_dir, eval_rows, shard_size=512)

    with ProcessJobLauncher(
        job="mpauc",
        model="ctr",
        min_workers=2,
        max_workers=2,
        n_samples=2048,
        passes=1,
        per_device_batch=32,
        ckpt_every=8,
        export=True,
        work_dir=str(tmp_path),
        extra_env={"EDL_VOCAB": "1024", "EDL_EVAL_DIR": eval_dir},
    ) as launcher:
        launcher.start(2)
        rcs = launcher.wait(timeout_s=240)
        _assert_succeeded(launcher, rcs)
        metric = launcher.kv("eval_metric")
        assert metric is not None, "no eval_metric published"
        step_s, auc_s = metric.split(":")
        auc = float(auc_s)
        assert 0.0 < auc <= 1.0 and int(step_s) > 0, metric
        # the synthetic CTR click model is learnable: AUC beats coin-flip
        assert auc > 0.55, metric
