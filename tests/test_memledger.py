"""Device memory ledger (edl_tpu/obs/memledger.py): replace-on-
reregister semantics, owner-scoped release, KV occupancy, the serving
engine's registration (incl. the crash/recover no-drift contract and
finalize-on-GC), and the EFFICIENCY surfaces (collector sample,
edl top strip)."""

import gc

import numpy as np
import pytest

import jax

from edl_tpu.models import llama
from edl_tpu.obs import costmodel as cm
from edl_tpu.obs import memledger
from edl_tpu.obs import metrics as om


def test_register_replace_release_semantics():
    reg = om.MetricsRegistry()
    led = memledger.MemoryLedger(registry=reg)
    led.register("a", "kv", 100, "kv")
    led.register("b", "kv", 50, "kv")
    assert led.total("kv") == 150
    assert reg.get("edl_hbm_bytes").value(category="kv") == 150
    # same key REPLACES (the recovery realloc shape), never adds
    led.register("a", "kv", 120, "kv")
    assert led.total("kv") == 170
    # re-register under a NEW category moves the bytes
    led.register("a", "kv", 80, "kv2")
    assert led.total("kv") == 50 and led.total("kv2") == 80
    assert reg.get("edl_hbm_bytes").value(category="kv") == 50
    assert led.release("b", "kv") == 50
    assert led.total("kv") == 0
    assert led.release("b", "kv") == 0  # absent: no-op
    assert led.owner_total("a") == 80


def test_owner_release_and_kv_occupancy():
    reg = om.MetricsRegistry()
    led = memledger.MemoryLedger(registry=reg)
    led.register("e1", "kv", 100, "kv")
    led.register("e1", "params", 200, "params")
    led.set_kv_usage("e1", 30, 100)
    led.set_kv_usage("e2", 10, 100)
    assert led.kv_occupancy() == pytest.approx(0.2)
    assert reg.get("edl_kv_occupancy_ratio").value() == pytest.approx(0.2)
    assert led.release_owner("e1") == 300
    assert led.total() == 0
    # e1's usage is gone too; e2's remains
    assert led.kv_occupancy() == pytest.approx(0.1)
    assert led.categories() == {}


def test_tree_nbytes_walks_nested_structures():
    a = np.zeros((4, 4), np.float32)  # 64 bytes
    tree = {"p": {"w": a, "records": {"q8": np.zeros(8, np.int8),
                                      "s8": np.zeros(2, np.float32)}},
            "l": [a, (a, None)], "scalar": 3}
    assert memledger.tree_nbytes(tree) == 64 * 3 + 8 + 8
    assert memledger.tree_nbytes(None) == 0


# ---------------------------------------------------------------------------
# engine integration


def _tiny_engine(**kw):
    from edl_tpu.serving.engine import ContinuousBatchingEngine

    cfg = llama.LlamaConfig.tiny(vocab=128)
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(0), cfg))()
    eng = ContinuousBatchingEngine(
        params, cfg, max_slots=2, max_len=32, horizon=4, **kw
    )
    return eng, cfg


def test_engine_registers_exact_kv_bytes_and_releases_on_gc():
    led = memledger.default_ledger()
    eng, cfg = _tiny_engine()
    owner = eng._ledger_owner
    expected = cm.kv_cache_bytes(
        cfg, slots=2, max_len=32,
        bytes_per_el=np.dtype(cfg.dtype).itemsize,
    )
    assert led.owner_total(owner, "kv") == expected
    assert led.owner_total(owner, "params") > 0
    assert led.owner_total(owner, "slot_state") > 0
    del eng
    gc.collect()
    assert led.owner_total(owner) == 0  # finalize released everything


def test_engine_kv_bytes_do_not_drift_across_recovery():
    """The ISSUE 8 fix contract: _recover -> _alloc_device_state
    re-registers under the same key, so edl_hbm_bytes{category=kv}
    stays EXACTLY one cache across crash/recover cycles."""
    from edl_tpu.utils import faults

    led = memledger.default_ledger()
    eng, cfg = _tiny_engine(max_recoveries=3)
    expected = led.owner_total(eng._ledger_owner, "kv")
    assert expected > 0
    for i in range(3):
        eng.submit(f"r{i}", [1 + i, 2, 3], 10)
    faults.arm("serve.dispatch:raise@n=2", seed=0)
    try:
        res = eng.run()
    finally:
        faults.disarm()
    assert eng.recoveries >= 1
    assert all(r.outcome in ("done", "eos") for r in res.values())
    assert led.owner_total(eng._ledger_owner, "kv") == expected


def test_engine_kv_occupancy_rises_and_clears():
    led = memledger.default_ledger()
    eng, _ = _tiny_engine()
    eng.submit("r0", [1, 2, 3, 4], 20)
    for _ in range(2):
        eng.step()
    assert led.kv_occupancy() > 0
    eng.run()
    eng.step()  # idle step refreshes usage to zero live tokens
    assert led.owner_total(eng._ledger_owner, "kv") > 0  # cache still held
    del eng
    gc.collect()


def test_crosscheck_shape():
    xc = memledger.default_ledger().crosscheck()
    if xc is None:
        pytest.skip("jax.live_arrays unavailable")
    assert set(xc) == {"ledger_bytes", "live_bytes", "unaccounted_bytes"}


# ---------------------------------------------------------------------------
# surfaces: collector EFFICIENCY + edl top strip


def test_serving_source_sample_carries_efficiency():
    from edl_tpu.monitor.collector import ServingSource
    from edl_tpu.serving.metrics import ServingMetrics

    reg = om.MetricsRegistry()
    metrics = ServingMetrics(registry=reg)
    meter = cm.EfficiencyMeter(cm.DevicePeak("t", 1e12, 1e11), registry=reg)
    meter.set_rates("decode", 5e11, 5e10)
    s = ServingSource(metrics).sample()
    assert s.efficiency["mfu_decode"] == pytest.approx(0.5)
    assert "EFFICIENCY" in s.render()
    assert s.to_record()["efficiency"]["bw_util_decode"] == pytest.approx(0.5)


def test_top_renders_efficiency_strip():
    from edl_tpu.obs.top import summarize

    reg = om.MetricsRegistry()
    meter = cm.EfficiencyMeter(cm.DevicePeak("t", 1e12, 1e11), registry=reg)
    meter.set_rates("decode", 5e11, 5e10)
    led = memledger.MemoryLedger(registry=reg)
    led.register("e", "kv", 3 << 30, "kv")
    led.set_kv_usage("e", 61, 100)
    fams = om.parse_prometheus_text(reg.render())
    text = "\n".join(summarize(fams))
    # the strip header: correctly spelled, 8 chars wide so the data
    # column lines up with TRAIN/SERVING/RESHARD (the PR 8 "EFFICNCY"
    # typo is pinned gone)
    assert "ROOFLINE " in text
    assert "EFFICNCY" not in text
    assert "decode: mfu=50.0%" in text
    assert "kv=3.00G" in text
    assert "kv_used=61.0%" in text
