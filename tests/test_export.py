"""Inference export: params-only servable artifacts (VERDICT r2 #6;
reference save_inference_model, example/ctr/ctr/train.py:169-180)."""

import os
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest

from edl_tpu.models import llama
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.runtime.export import (
    export_from_checkpoint,
    export_params,
    export_status,
    load_export,
)
from edl_tpu.train.trainer import TrainState, shard_state


def test_export_roundtrip_and_forward_eval(tmp_path, cpu_devices):
    """A fresh consumer loads the latest export and runs forward-only
    eval — no TrainState, optimizer, or mesh required."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    d = export_params(str(tmp_path), params, step=7, dtype="float32")
    assert os.path.basename(d) == "step-00000007"

    loaded, doc = load_export(str(tmp_path))
    assert doc["step"] == 7
    toks = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab
    ref = llama.forward(params, np.asarray(toks), cfg)
    out = llama.forward(loaded, np.asarray(toks), cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_export_bf16_cast_halves_bytes(tmp_path):
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    export_params(str(tmp_path / "f32"), params, 1, dtype="float32")
    export_params(str(tmp_path / "bf16"), params, 1, dtype="bfloat16")
    f32 = os.path.getsize(tmp_path / "f32" / "step-00000001" / "params.npz")
    bf16 = os.path.getsize(tmp_path / "bf16" / "step-00000001" / "params.npz")
    assert bf16 < 0.6 * f32, (bf16, f32)
    loaded, doc = load_export(str(tmp_path / "bf16"))
    import ml_dtypes

    assert loaded["embed"].dtype == np.dtype(ml_dtypes.bfloat16)
    # bf16 round-trips exactly from its own values
    np.testing.assert_allclose(
        np.asarray(loaded["embed"], np.float32),
        np.asarray(params["embed"]).astype(ml_dtypes.bfloat16).astype(np.float32),
    )


def test_latest_pointer_moves_monotonically(tmp_path):
    params = {"w": np.ones((4, 4), np.float32)}
    export_params(str(tmp_path), params, 5)
    export_params(str(tmp_path), {"w": 2 * np.ones((4, 4), np.float32)}, 9)
    _, doc = load_export(str(tmp_path))
    assert doc["step"] == 9
    # a stalled writer finishing late must NOT regress the pointer
    export_params(str(tmp_path), params, 7)
    _, doc = load_export(str(tmp_path))
    assert doc["step"] == 9


def test_export_gc_keeps_two(tmp_path):
    params = {"w": np.ones((4, 4), np.float32)}
    for s in (1, 2, 3, 4, 5):
        export_params(str(tmp_path), params, s)
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("step-"))
    assert dirs == ["step-00000004", "step-00000005"], dirs
    _, doc = load_export(str(tmp_path))
    assert doc["step"] == 5


def test_export_from_sharded_checkpoint(tmp_path, cpu_devices):
    """The commit-leader path: assemble params (only) out of a sharded
    fsdp checkpoint no single process could snapshot."""
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=2, fsdp=4)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(3), cfg)
    tx = optax.adam(1e-3)
    pspecs = llama.param_pspecs(cfg, plan)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)

    ckpt_root = str(tmp_path / "ckpt")
    snap = ckpt.snapshot_local(state)
    fname = ckpt.save_shards(ckpt_root, snap, 0, 1, host_leaves=True)
    ckpt.write_manifest(ckpt_root, snap, [fname], {})

    export_root = str(tmp_path / "export")
    d = export_from_checkpoint(ckpt_root, export_root, dtype="float32")
    assert d is not None
    loaded, doc = load_export(export_root)
    assert doc["step"] == 0 and "opt" not in str(sorted(doc["shapes"]))
    for (key, ref) in [
        ("embed", params["embed"]),
        (("layers", "wq"), params["layers"]["wq"]),
    ]:
        got = loaded[key[0]][key[1]] if isinstance(key, tuple) else loaded[key]
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)
    # optimizer state never ships
    assert all(k.split("/")[0] in params for k in doc["shapes"])
    # re-export of the same step is skipped (monotonic)
    assert export_from_checkpoint(ckpt_root, export_root) is None


def test_cli_export_status(tmp_path):
    params = {"w": np.ones((8, 8), np.float32)}
    export_params(str(tmp_path), params, 3)
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "edl_tpu.cli",
            "export-status",
            str(tmp_path),
            "--fetch",
            str(tmp_path / "fetched"),
        ],
        capture_output=True,
        text=True,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        },
    )
    assert out.returncode == 0, out.stderr
    assert "step=3" in out.stdout and "params=64" in out.stdout
    assert os.path.exists(tmp_path / "fetched" / "params.npz")


def test_no_export_is_a_clean_miss(tmp_path):
    assert export_status(str(tmp_path)) is None
    with pytest.raises(FileNotFoundError):
        load_export(str(tmp_path))


def test_export_restores_list_structured_params(tmp_path, cpu_devices):
    """Flat leaf paths erase the list-vs-dict distinction; the loader
    must rebuild integer-keyed levels as LISTS (ctr's params['mlp'] is
    a layer list — `for layer in params['mlp']` must iterate layers,
    not key strings)."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import ctr

    params = ctr.init_params(jax.random.PRNGKey(0), vocab=512)
    export_params(str(tmp_path), params, step=1, dtype="float32")
    loaded, _ = load_export(str(tmp_path))
    assert isinstance(loaded["mlp"], list) and len(loaded["mlp"]) == len(
        params["mlp"]
    )
    rows = ctr.synthetic_batch(np.random.RandomState(0), 64, vocab=512)
    want = ctr.forward(
        params, jnp.asarray(rows["dense"]), jnp.asarray(rows["sparse"])
    )
    got = ctr.forward(
        loaded, jnp.asarray(rows["dense"]), jnp.asarray(rows["sparse"])
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
