"""Elastic serving fleet: router, replica table, supervisor, scaler.

Everything here is jax-free — the routing/supervision logic runs
against fake transports, fake spawn/probe/drain hooks, and a fake
engine behind the REAL replica HTTP server, so the orchestration
contracts (token-identical failover replay, drain-before-evict with
zero lost/duplicated requests, rolling swaps holding the READY floor,
hysteresis-damped scaling) are pinned without booting a model. The
real-subprocess end-to-end lives in scripts/exp_fleet.py (chaos lane,
run_tests.sh phase 11).
"""

import threading
import time

import pytest

from edl_tpu.obs import events as flight
from edl_tpu.obs.metrics import MetricsRegistry, parse_prometheus_text
from edl_tpu.obs.top import summarize
from edl_tpu.serving import router as rt
from edl_tpu.serving.fleet import (
    FleetScaler,
    ReplicaHandle,
    ReplicaSupervisor,
    ServingFleet,
)
from edl_tpu.serving.replica import ReplicaServer
from edl_tpu.serving.router import (
    DEAD,
    DRAINING,
    READY,
    SUSPECT,
    ReplicaTable,
    RouteRejected,
    Router,
    http_json,
)
from edl_tpu.serving.scheduler import Request
from edl_tpu.utils import faults


def _fake_model(prompt, max_new):
    """Deterministic stateless 'greedy decode': token j depends only on
    prompt + previously generated tokens, so serving (prompt + got,
    max_new - len(got)) continues the SAME sequence — the same replay
    contract the real engine's greedy decode gives the router."""
    seq = list(prompt)
    out = []
    for _ in range(max_new):
        t = (sum(seq) * 31 + len(seq)) % 211
        out.append(t)
        seq.append(t)
    return out


def _table(n=2, registry=None, **kw):
    table = ReplicaTable(registry=registry or MetricsRegistry(), **kw)
    for i in range(n):
        table.add(f"r{i}", f"fake://r{i}")
        table.set_state(f"r{i}", READY)
    return table


def _serving_transport(served=None):
    """Transport that 'decodes' with _fake_model on whatever replica
    gets picked."""

    def transport(ref, payload, on_tokens):
        toks = _fake_model(payload["prompt"], payload["max_new"])
        on_tokens(toks)
        if served is not None:
            served.append((ref.id, payload["rid"]))
        return "done"

    return transport


# -- replica table: state machine + routing ---------------------------------


def test_table_probe_state_machine_and_sticky_states():
    table = _table(1, suspect_after=1, dead_after=3)
    assert table.mark_probe("r0", ok=False) == SUSPECT
    # one good probe resurrects and resets the streak
    assert table.mark_probe("r0", ok=True, queue_depth=5) == READY
    assert table.get("r0").queue_depth == 5
    assert table.mark_probe("r0", ok=False) == SUSPECT
    assert table.mark_probe("r0", ok=False) == SUSPECT
    assert table.mark_probe("r0", ok=False) == DEAD
    # DEAD is sticky: a late good probe must not resurrect
    assert table.mark_probe("r0", ok=True) == DEAD
    table2 = _table(1)
    table2.set_state("r0", DRAINING)
    # DRAINING is sticky against probes (the supervisor owns it)
    assert table2.mark_probe("r0", ok=True) == DRAINING
    assert table2.acquire() is None


def test_table_least_load_session_pin_and_affinity():
    table = _table(3)
    # least queue_depth + inflight wins
    table.mark_probe("r0", ok=True, queue_depth=9)
    table.mark_probe("r1", ok=True, queue_depth=0)
    table.mark_probe("r2", ok=True, queue_depth=9)
    ref = table.acquire()
    assert ref.id == "r1"
    # session pin: same session sticks to its replica while READY
    ref2 = table.acquire(session="sess")
    for _ in range(3):
        again = table.acquire(session="sess")
        assert again.id == ref2.id
        table.release(again.id)
    # prefix affinity is deterministic while within the slack
    table3 = _table(3, affinity_slack=100)
    picks = {table3.acquire(prefix_key="1,2,3").id for _ in range(4)}
    assert len(picks) == 1
    # ... but never overrides a big load imbalance
    table4 = _table(2, affinity_slack=1)
    affine = table4.acquire(prefix_key="k").id
    other = "r0" if affine == "r1" else "r1"
    table4.mark_probe(affine, ok=True, queue_depth=50)
    assert table4.acquire(prefix_key="k").id == other


def test_table_acquire_excludes_and_remove_purges_sessions():
    table = _table(2)
    ref = table.acquire(session="s", exclude=["r0"])
    assert ref.id == "r1"
    assert table.acquire(exclude=["r0", "r1"]) is None
    table.remove("r1")
    # the pin died with its replica: no stale session entry remains
    assert table.acquire(session="s").id == "r0"


# -- router: failover replay, budgets, requeue ------------------------------


def test_router_failover_replays_token_identical():
    """A replica that dies mid-stream costs nothing: the router
    replays prompt + received on a survivor and the final stream is
    identical to a failure-free run."""
    table = _table(2, registry=MetricsRegistry())
    reg = MetricsRegistry()
    prompt, max_new = [3, 1, 4, 1, 5], 8
    want = _fake_model(prompt, max_new)
    first = {"armed": True}

    def transport(ref, payload, on_tokens):
        toks = _fake_model(payload["prompt"], payload["max_new"])
        if first.pop("armed", None):
            on_tokens(toks[:3])  # 3 tokens escape, then the wire dies
            raise ConnectionError("replica gone mid-stream")
        on_tokens(toks)
        return "done"

    router = Router(table, transport=transport, registry=reg,
                    backoff_base_s=0.0, sleep=lambda s: None)
    res = router.generate(Request(rid="x", prompt=prompt, max_new=max_new))
    assert res.outcome == "done"
    assert res.tokens == want
    assert res.failovers == 1
    # the failed replica took a probe strike and the events tell the
    # postmortem story: failover + recover carrying the rid
    kinds = {r["kind"] for r in flight.default_recorder().records()}
    assert {"replica.failover", "router.recover"} <= kinds


def test_router_failover_budget_bounded():
    table = _table(3, registry=MetricsRegistry())

    def transport(ref, payload, on_tokens):
        raise ConnectionError("always down")

    router = Router(table, transport=transport, max_failovers=1,
                    registry=MetricsRegistry(),
                    backoff_base_s=0.0, sleep=lambda s: None)
    res = router.generate(Request(rid="x", prompt=[1], max_new=4))
    assert res.outcome == "failed"
    assert res.failovers == 2  # initial + max_failovers, then give up


def test_router_rejection_is_terminal():
    table = _table(2, registry=MetricsRegistry())
    calls = []

    def transport(ref, payload, on_tokens):
        calls.append(ref.id)
        raise RouteRejected("over_capacity", "queue full")

    router = Router(table, transport=transport,
                    registry=MetricsRegistry())
    res = router.generate(Request(rid="x", prompt=[1], max_new=4))
    assert res.outcome == "rejected:over_capacity"
    assert len(calls) == 1  # no retry storm on an admission refusal


def test_router_requeued_reroutes_without_failover_budget():
    """A drain-displaced request ("requeued" terminal, zero tokens)
    re-routes whole and finishes elsewhere — without burning failover
    budget and without a duplicate run."""
    table = _table(2, registry=MetricsRegistry())
    served = []

    def transport(ref, payload, on_tokens):
        if not served:
            served.append(("drained", ref.id))
            return "requeued"
        served.append((ref.id, payload["rid"]))
        on_tokens(_fake_model(payload["prompt"], payload["max_new"]))
        return "done"

    router = Router(table, transport=transport, max_failovers=0,
                    registry=MetricsRegistry())
    res = router.generate(Request(rid="x", prompt=[2, 7], max_new=5))
    assert res.outcome == "done"
    assert res.tokens == _fake_model([2, 7], 5)
    assert res.failovers == 0
    assert len([s for s in served if s[0] != "drained"]) == 1


def test_router_deadline_timeout_without_replicas():
    table = ReplicaTable(registry=MetricsRegistry())  # empty fleet
    clk = {"t": 0.0}

    def clock():
        clk["t"] += 0.5
        return clk["t"]

    router = Router(table, transport=_serving_transport(),
                    registry=MetricsRegistry(), pick_wait_s=10.0,
                    clock=clock, sleep=lambda s: None)
    res = router.generate(
        Request(rid="x", prompt=[1], max_new=2, deadline_s=2.0)
    )
    assert res.outcome == "timeout"
    assert res.tokens == []


# -- fault sites on the real paths ------------------------------------------


def test_fault_site_router_forward_armed_drop_fails_over():
    table = _table(2, registry=MetricsRegistry())
    served = []
    router = Router(table, transport=_serving_transport(served),
                    registry=MetricsRegistry(),
                    backoff_base_s=0.0, sleep=lambda s: None)
    faults.arm("router.forward:drop@n=1", seed=0)
    try:
        res = router.generate(Request(rid="x", prompt=[5], max_new=3))
        assert res.outcome == "done"
        assert res.tokens == _fake_model([5], 3)
        assert res.failovers == 1
        assert faults.counts().get("router.forward") == 1
    finally:
        faults.disarm()
    assert len(served) == 1  # exactly one replica ran it


def test_fault_site_replica_spawn_armed_raise_retries():
    table = ReplicaTable(registry=MetricsRegistry())
    health = {"status": "ok", "queue_depth": 0}
    sup = ReplicaSupervisor(
        table,
        spawn_fn=lambda rid, gen: ReplicaHandle(
            id=rid, generation=gen, url=f"fake://{rid}"
        ),
        probe_fn=lambda url: dict(health),
        drain_fn=lambda url: {"residual": [], "served": 0},
        spawn_retries=1, sleep=lambda s: None,
    )
    faults.arm("replica.spawn:raise@n=1", seed=0)
    try:
        rid = sup.spawn()
        sup.wait_ready(rid)
        assert table.get(rid).state == READY
        assert faults.counts().get("replica.spawn") == 1
    finally:
        faults.disarm()
    # an exhausted retry budget surfaces instead of half-spawning
    faults.arm("replica.spawn:raise@every=1", seed=0)
    try:
        with pytest.raises(RuntimeError, match="failed to spawn"):
            sup.spawn()
    finally:
        faults.disarm()


def test_fault_site_replica_health_flap_suspects_then_recovers():
    table = ReplicaTable(registry=MetricsRegistry(), suspect_after=1,
                         dead_after=3)
    sup = ReplicaSupervisor(
        table,
        spawn_fn=lambda rid, gen: ReplicaHandle(
            id=rid, generation=gen, url=f"fake://{rid}"
        ),
        probe_fn=lambda url: {"status": "ok", "queue_depth": 0},
        sleep=lambda s: None,
    )
    rid = sup.spawn()
    sup.wait_ready(rid)
    faults.arm("replica.health:raise@every=1,max=2", seed=0)
    try:
        assert sup.probe_once(rid) == SUSPECT
        assert sup.probe_once(rid) == SUSPECT
        assert faults.counts().get("replica.health") == 2
        # the flap clears: resurrect, and say so for the postmortem
        assert sup.probe_once(rid) == READY
    finally:
        faults.disarm()
    recs = flight.default_recorder().records()
    recov = [r for r in recs if r["kind"] == "replica.recover"
             and r.get("corr", {}).get("worker") == rid]
    assert recov, "SUSPECT→READY resurrect must emit replica.recover"


# -- supervisor: death respawn, drain-before-evict, rolling swap ------------


class _FakeFleetEnv:
    """Shared state behind the supervisor's spawn/probe/drain fakes."""

    def __init__(self):
        self.health = {}   # url -> health doc (or ConnectionError)
        self.residual = {}  # url -> residual docs handed out on drain
        self.drained = []

    def spawn_fn(self, rid, gen):
        url = f"fake://{rid}"
        self.health[url] = {"status": "ok", "queue_depth": 0}
        return ReplicaHandle(id=rid, generation=gen, url=url)

    def probe_fn(self, url):
        doc = self.health[url]
        if isinstance(doc, Exception):
            raise doc
        return dict(doc)

    def drain_fn(self, url):
        self.drained.append(url)
        return {"residual": self.residual.get(url, []), "served": 1}


def _supervisor(env, table=None, **kw):
    table = table or ReplicaTable(registry=MetricsRegistry())
    kw.setdefault("sleep", lambda s: None)
    return ReplicaSupervisor(
        table, spawn_fn=env.spawn_fn, probe_fn=env.probe_fn,
        drain_fn=env.drain_fn, **kw
    ), table


def test_supervisor_death_respawns_to_target():
    env = _FakeFleetEnv()
    sup, table = _supervisor(env)
    ids = [sup.spawn() for _ in range(2)]
    for rid in ids:
        sup.wait_ready(rid)
    sup._target = 2
    # r0 stops answering: three strikes walk it to DEAD, the
    # supervisor reaps it and heals the fleet back to target
    env.health["fake://r0"] = ConnectionError("kill -9")
    for _ in range(3):
        sup.probe_once("r0")
    assert table.get("r0") is None
    alive = table.ids()
    assert len(alive) == 2 and "r1" in alive
    new = [r for r in alive if r != "r1"][0]
    assert table.get(new).state == READY
    kinds = [r["kind"] for r in flight.default_recorder().records()]
    assert "replica.dead" in kinds and "replica.recover" in kinds


def test_supervisor_reaps_router_declared_dead():
    # the ROUTER's mark_probe(ok=False) calls (one per failed forward)
    # can walk a replica to DEAD between prober sweeps; DEAD is sticky,
    # so the next probe_once must reap it or the zombie entry sits in
    # the table forever and the fleet never heals back to target
    env = _FakeFleetEnv()
    sup, table = _supervisor(env)
    ids = [sup.spawn() for _ in range(2)]
    for rid in ids:
        sup.wait_ready(rid)
    sup._target = 2
    for _ in range(table.dead_after):
        table.mark_probe("r0", ok=False)
    assert table.get("r0").state == DEAD
    assert sup.probe_once("r0") == DEAD
    assert table.get("r0") is None
    alive = table.ids()
    assert len(alive) == 2 and "r1" in alive
    new = [r for r in alive if r != "r1"][0]
    assert table.get(new).state == READY


def test_supervisor_drain_before_evict_requeues_residual():
    env = _FakeFleetEnv()
    sup, table = _supervisor(env)
    reg = MetricsRegistry()
    for _ in range(2):
        sup.wait_ready(sup.spawn())
    sup._target = 2
    env.residual["fake://r0"] = [
        {"rid": "leftover", "prompt": [4, 2], "max_new": 3},
    ]
    served = []
    router = Router(table, transport=_serving_transport(served),
                    registry=reg)
    fleet = ServingFleet(sup, router)
    done = fleet.scale_down(victim="r0")
    # drain happened BEFORE the evict, the residual reran through the
    # router on the survivor, and nothing was lost or duplicated
    assert env.drained == ["fake://r0"]
    assert table.get("r0") is None
    assert [r.rid for r in done] == ["leftover"]
    assert done[0].outcome == "done"
    assert done[0].tokens == _fake_model([4, 2], 3)
    assert served == [("r1", "leftover")]
    assert fleet.results["leftover"].outcome == "done"
    kinds = [r["kind"] for r in flight.default_recorder().records()]
    assert kinds.count("replica.drain") >= 1
    assert kinds.count("replica.evict") >= 1


def test_supervisor_rolling_swap_holds_ready_floor():
    env = _FakeFleetEnv()
    sup, table = _supervisor(env)
    reg = MetricsRegistry()
    n = 3
    for _ in range(n):
        sup.wait_ready(sup.spawn())
    sup._target = n
    router = Router(table, transport=_serving_transport(), registry=reg)
    fleet = ServingFleet(sup, router)
    gen = fleet.rolling_swap()
    assert gen == 1
    # one-at-a-time: READY never dropped below N-1
    assert sup.min_ready_observed == n - 1
    reps = table.snapshot()
    assert len(reps) == n
    assert all(r.generation == 1 and r.state == READY for r in reps)
    # original ids all gone — every replica is a fresh process
    assert not {f"r{i}" for i in range(n)} & set(table.ids())


def test_supervisor_swap_residuals_requeue_through_router():
    env = _FakeFleetEnv()
    sup, table = _supervisor(env)
    for _ in range(2):
        sup.wait_ready(sup.spawn())
    sup._target = 2
    env.residual["fake://r1"] = [
        {"rid": "displaced", "prompt": [9], "max_new": 2},
    ]
    served = []
    router = Router(table, transport=_serving_transport(served),
                    registry=MetricsRegistry())
    fleet = ServingFleet(sup, router)
    fleet.rolling_swap()
    assert fleet.results["displaced"].outcome == "done"
    assert [s[1] for s in served] == ["displaced"]


# -- fleet scaler: hysteresis + SLO bypass ----------------------------------


class _FakeScalableFleet:
    def __init__(self):
        self.ups = 0
        self.downs = 0

    def scale_up(self):
        self.ups += 1

    def scale_down(self):
        self.downs += 1


def test_fleet_scaler_depth_thresholds_and_cooldown():
    table = _table(2)
    clk = {"t": 0.0}
    scaler = FleetScaler(
        table, min_replicas=1, max_replicas=4,
        depth_high=4.0, depth_low=0.5, cooldown_s=30.0,
        clock=lambda: clk["t"],
    )
    fleet = _FakeScalableFleet()
    # hot: mean depth 6 > 4 → up
    for rid in table.ids():
        table.mark_probe(rid, ok=True, queue_depth=6)
    assert scaler.tick(fleet) == "up"
    assert fleet.ups == 1
    # still hot, but inside the cooldown → damped (no thrash)
    assert scaler.tick(fleet) is None
    clk["t"] = 31.0
    assert scaler.tick(fleet) == "up"
    # idle: mean depth 0 < 0.5 → down, after the cooldown
    for rid in table.ids():
        table.mark_probe(rid, ok=True, queue_depth=0)
    clk["t"] = 62.0
    assert scaler.tick(fleet) == "down"
    assert fleet.downs == 1
    # at min_replicas nothing scales down
    table5 = _table(1)
    scaler5 = FleetScaler(table5, min_replicas=1, max_replicas=4,
                          cooldown_s=0.0, clock=lambda: 0.0)
    assert scaler5.decide() is None


def test_fleet_scaler_slo_breach_bypasses_cooldown():
    table = _table(1)
    ttft = {"p95": 0.01}
    scaler = FleetScaler(
        table, min_replicas=1, max_replicas=4, cooldown_s=1e9,
        ttft_slo_s=0.2, ttft_p95_s=lambda: ttft["p95"],
        clock=lambda: 0.0,
    )
    fleet = _FakeScalableFleet()
    assert scaler.tick(fleet) is None  # SLO fine, load fine
    ttft["p95"] = 0.9  # users are missing deadlines
    assert scaler.tick(fleet) == "up"
    assert scaler.tick(fleet) == "up"  # breach keeps bypassing
    assert fleet.ups == 2


# -- replica HTTP server over a fake engine ---------------------------------


class _FakeQueue:
    def __init__(self):
        self._q = []

    @property
    def depth(self):
        return len(self._q)

    def push(self, r):
        self._q.append(r)

    def pop(self):
        return self._q.pop(0) if self._q else None


class _FakeEngine:
    """Engine-shaped double for the replica server: one step serves one
    queued request whole via _fake_model. ``serve=False`` freezes the
    queue (a replica that never admits — the drain test's setup)."""

    def __init__(self, serve=True):
        self.queue = _FakeQueue()
        self.results = {}
        self._inflight = []
        self._slots = []
        self._draining = False
        self._serve = serve

    @property
    def active_slots(self):
        return 0

    @property
    def has_work(self):
        return self._serve and not self._draining and self.queue.depth > 0

    @property
    def draining(self):
        return self._draining

    def submit(self, rid, prompt, max_new, **kw):
        self.queue.push(Request(rid=rid, prompt=list(prompt),
                                max_new=int(max_new)))

    def step(self):
        req = self.queue.pop()
        if req is not None:
            self.results[req.rid] = type(
                "R", (), {"rid": req.rid,
                          "tokens": _fake_model(req.prompt, req.max_new),
                          "outcome": "done"},
            )()

    def half_close(self):
        self._draining = True

    def take_residual(self):
        out = []
        while True:
            r = self.queue.pop()
            if r is None:
                break
            out.append(r)
        return out


def test_replica_server_streams_and_reports_health():
    eng = _FakeEngine()
    with ReplicaServer(eng, generation=7,
                       registry=MetricsRegistry()) as srv:
        hz = http_json(srv.url, "/healthz")
        assert hz["status"] == "ok" and hz["generation"] == 7
        table = ReplicaTable(registry=MetricsRegistry())
        table.add("r0", srv.url)
        table.set_state("r0", READY)
        router = Router(table, registry=MetricsRegistry())
        res = router.generate(Request(rid="q1", prompt=[1, 2], max_new=4))
        assert res.outcome == "done"
        assert res.tokens == _fake_model([1, 2], 4)


def test_replica_drain_over_http_requeues_attached_stream():
    """The full drain handover over the real wire: a request queued on
    a never-admitting replica gets displaced by /drain, its attached
    router stream ends with the "requeued" terminal, and the SAME
    router call finishes it on the second replica — exactly once."""
    frozen, live = _FakeEngine(serve=False), _FakeEngine()
    with ReplicaServer(frozen, registry=MetricsRegistry()) as s0, \
            ReplicaServer(live, registry=MetricsRegistry()) as s1:
        table = ReplicaTable(registry=MetricsRegistry())
        table.add("r0", s0.url)
        table.add("r1", s1.url)
        table.set_state("r0", READY)
        # r1 joins mid-flight, after the drain — like a swap target
        router = Router(table, registry=MetricsRegistry(),
                        pick_wait_s=10.0)
        out = {}
        t = threading.Thread(target=lambda: out.setdefault(
            "res", router.generate(
                Request(rid="moved", prompt=[6, 6], max_new=3))
        ))
        t.start()
        deadline = time.monotonic() + 5.0
        while frozen.queue.depth == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert frozen.queue.depth == 1, "request never reached r0"
        doc = http_json(s0.url, "/drain", body={})
        assert [d["rid"] for d in doc["residual"]] == ["moved"]
        table.set_state("r0", DRAINING)
        table.set_state("r1", READY)
        t.join(timeout=10)
        assert not t.is_alive()
        res = out["res"]
        assert res.outcome == "done"
        assert res.tokens == _fake_model([6, 6], 3)
        assert res.failovers == 0  # a drain is not a failure
        assert live.results["moved"].outcome == "done"
        assert "moved" not in frozen.results or (
            frozen.results["moved"].outcome == "requeued"
        )


def test_replica_server_rejects_while_draining():
    eng = _FakeEngine()
    with ReplicaServer(eng, registry=MetricsRegistry()) as srv:
        http_json(srv.url, "/drain", body={})
        with pytest.raises(RouteRejected) as ei:
            from edl_tpu.serving.router import HttpTransport

            HttpTransport()(
                rt.ReplicaRef(id="r0", url=srv.url, generation=0),
                {"rid": "x", "prompt": [1], "max_new": 1},
                lambda toks: None,
            )
        assert ei.value.reason == "draining"


# -- observability ----------------------------------------------------------


def test_top_fleet_serving_strip():
    reg = MetricsRegistry()
    table = _table(2, registry=reg)
    router = Router(table, transport=_serving_transport(), registry=reg)
    router.generate(Request(rid="x", prompt=[1, 2, 3], max_new=4))
    lines = summarize(parse_prometheus_text(reg.render()))
    strip = [ln for ln in lines if "replicas_up=" in ln]
    assert len(strip) == 1
    assert "replicas_up=2" in strip[0]
    assert "routed=1" in strip[0]
    assert "failovers=0" in strip[0]
