"""KubeCluster against the in-memory API server (tests/fake_kube.py).

Covers the full L1 surface the reference exercises through client-go
(reference: pkg/cluster.go:79-291): census math, worker-group CRUD
with optimistic concurrency, coordinator CRUD, pod counting, the
TrainingJob CRD source, and an end-to-end control-plane run
(controller + updater + autoscaler) over the fake API — the
integration harness SURVEY §4 says the reference's fake clientset was
meant for but never got.
"""

import time

import pytest

from edl_tpu.api import job as job_api
from edl_tpu.api.job import JobPhase, TrainingJob
from edl_tpu.api.parser import JobParser
from edl_tpu.cluster.base import ConflictError
from edl_tpu.cluster.kube import KubeApi, KubeCluster, KubeJobSource
from tests.fake_kube import FakeKubeServer


@pytest.fixture()
def server():
    s = FakeKubeServer()
    yield s
    s.close()


@pytest.fixture()
def cluster(server):
    return KubeCluster(KubeApi(server.url), worker_image="edl-tpu/worker:test")


def _job(name="demo", min_r=2, max_r=8, chips=1, ft=True) -> TrainingJob:
    return TrainingJob.from_dict(
        {
            "apiVersion": "edl-tpu.org/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "fault_tolerant": ft,
                "worker": {
                    "entrypoint": "python train.py",
                    "min_replicas": min_r,
                    "max_replicas": max_r,
                    "resources": {
                        "requests": {"cpu": "2", "memory": "4Gi", "tpu": chips},
                        "limits": {"tpu": chips},
                    },
                },
            },
        }
    )


def test_inquiry_resource_counts_nodes_and_pods(server, cluster):
    server.add_node("n0", cpu="8", memory="32Gi", tpu=4)
    server.add_node("n1", cpu="8", memory="32Gi", tpu=4)
    r = cluster.inquiry_resource()
    assert r.chip_total == 8
    assert r.cpu_total_milli == 16_000
    from edl_tpu.api.resources import mem_mega

    assert r.mem_total_mega == 2 * mem_mega("32Gi")

    # place a worker group; its pods' requests must be subtracted
    plan = JobParser().parse_to_workers(_job(min_r=2, chips=2))
    cluster.create_worker_group(plan)
    server.reconcile_pods()
    r = cluster.inquiry_resource()
    assert r.chip_request == 4  # 2 pods x 2 chips
    assert r.cpu_request_milli == 4_000
    idle_chips = sum(r.hosts.chips_free.values())
    assert idle_chips == 8 - 4


def test_worker_group_crud_and_conflict(server, cluster):
    job = _job()
    plan = JobParser().parse_to_workers(job)
    group = cluster.create_worker_group(plan)
    assert group.parallelism == 2

    got = cluster.get_worker_group(job)
    assert got.name == "demo-worker"
    assert got.parallelism == 2

    got.parallelism = 5
    cluster.update_worker_group(got)
    fresh = cluster.get_worker_group(job)
    assert fresh.parallelism == 5

    # stale resource_version must conflict (reference: UpdateTrainerJob
    # retry loop depends on this, pkg/autoscaler.go:346-370)
    got.parallelism = 6  # `got` still carries the pre-update version
    with pytest.raises(ConflictError):
        cluster.update_worker_group(got)

    cluster.delete_worker_group("default", "demo-worker")
    with pytest.raises(KeyError):
        cluster.get_worker_group(job)
    cluster.delete_worker_group("default", "demo-worker")  # idempotent


def test_worker_job_manifest_shape(server, cluster):
    job = _job(chips=4)
    job.spec.accelerator_type = "v5e"
    cluster.create_worker_group(JobParser().parse_to_workers(job))
    obj = server.get_object("batch/v1", "jobs", "default", "demo-worker")
    spec = obj["spec"]
    assert spec["parallelism"] == 2
    assert spec["backoffLimit"] == 8  # FT: tolerate up to max_replicas
    pod = spec["template"]["spec"]
    assert pod["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "v5e"
    }
    c = pod["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == 4
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["EDL_JOB_NAME"] == "demo"
    assert env["EDL_WORKERS_MAX"] == "8"
    assert env["EDL_FAULT_TOLERANT"] == "1"
    assert env["EDL_COORDINATOR"].startswith("demo-coordinator:")


def test_volumes_rendered_into_pod_templates(server, cluster):
    """Volumes/VolumeMounts (reference: types.go:54-56) land in BOTH the
    worker Job and the coordinator Deployment pod specs — plus the
    EDL_DATA_DIR/EDL_CKPT_DIR env contract pointing into the mounts."""
    job = _job(name="vol")
    job.spec.data_dir = "/data/ds"
    job.spec.checkpoint_dir = "/ckpt/vol"
    job.spec.volumes = [
        job_api.VolumeSpec("dataset", {"persistentVolumeClaim": {"claimName": "ds"}}),
        job_api.VolumeSpec("ckpt", {"hostPath": {"path": "/mnt/ckpt"}}),
    ]
    job.spec.volume_mounts = [
        job_api.VolumeMountSpec("dataset", "/data", read_only=True),
        job_api.VolumeMountSpec("ckpt", "/ckpt"),
    ]
    parser = JobParser()
    assert parser.validate(job) == []  # ckpt under a mount: no warnings
    cluster.create_worker_group(parser.parse_to_workers(job))
    cluster.create_coordinator(parser.parse_to_coordinator(job))

    obj = server.get_object("batch/v1", "jobs", "default", "vol-worker")
    pod = obj["spec"]["template"]["spec"]
    assert {v["name"] for v in pod["volumes"]} == {"dataset", "ckpt"}
    assert pod["volumes"][0]["persistentVolumeClaim"] == {"claimName": "ds"}
    c = pod["containers"][0]
    assert c["volumeMounts"] == [
        {"name": "dataset", "mountPath": "/data", "readOnly": True},
        {"name": "ckpt", "mountPath": "/ckpt"},
    ]
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["EDL_DATA_DIR"] == "/data/ds"
    assert env["EDL_CKPT_DIR"] == "/ckpt/vol"

    dep = server.get_object("apps/v1", "deployments", "default", "vol-coordinator")
    dpod = dep["spec"]["template"]["spec"]
    assert {v["name"] for v in dpod["volumes"]} == {"dataset", "ckpt"}
    assert dpod["containers"][0]["volumeMounts"][0]["mountPath"] == "/data"


def test_volume_validation_rejects_bad_mounts():
    job = _job(name="badvol")
    job.spec.volumes = [job_api.VolumeSpec("a", {"emptyDir": {}})]
    job.spec.volume_mounts = [job_api.VolumeMountSpec("missing", "/x")]
    with pytest.raises(Exception, match="references no declared volume"):
        JobParser().validate(job)
    job.spec.volume_mounts = [job_api.VolumeMountSpec("a", "relative/path")]
    with pytest.raises(Exception, match="absolute"):
        JobParser().validate(job)
    job.spec.volume_mounts = []
    job.spec.volumes.append(job_api.VolumeSpec("a", {"emptyDir": {}}))
    with pytest.raises(Exception, match="duplicate"):
        JobParser().validate(job)


def test_non_ft_job_gets_zero_backoff(server, cluster):
    job = _job(name="rigid", min_r=2, max_r=2, ft=False)
    cluster.create_worker_group(JobParser().parse_to_workers(job))
    obj = server.get_object("batch/v1", "jobs", "default", "rigid-worker")
    assert obj["spec"]["backoffLimit"] == 0


def test_coordinator_crud(server, cluster):
    job = _job()
    parser = JobParser()
    parser.validate(job)  # fills the port default (reference: jobparser.go:50-51)
    plan = parser.parse_to_coordinator(job)
    coord = cluster.create_coordinator(plan)
    assert coord.name == "demo-coordinator"

    server.reconcile_pods()
    got = cluster.get_coordinator("default", "demo-coordinator")
    assert got.ready_replicas == 1
    assert got.endpoint == "demo-coordinator.default.svc:7164"

    svc = server.get_object("v1", "services", "default", "demo-coordinator")
    assert svc["spec"]["ports"][0]["port"] == 7164

    cluster.delete_coordinator("default", "demo-coordinator")
    with pytest.raises(KeyError):
        cluster.get_coordinator("default", "demo-coordinator")
    assert server.get_object("v1", "services", "default", "demo-coordinator") is None
    cluster.delete_coordinator("default", "demo-coordinator")  # idempotent


def test_job_pods_census(server, cluster):
    job = _job(min_r=3)
    cluster.create_worker_group(JobParser().parse_to_workers(job))
    server.reconcile_pods()
    assert cluster.job_pods(job) == (3, 3, 0)
    server.set_pod_phase("default", "demo-worker-0", "Pending")
    assert cluster.job_pods(job) == (3, 2, 1)


def test_fake_reconciler_scale_cycle_past_ten(server, cluster):
    """Regression: lexicographic pod sorting lost pods on a 12->10->12
    cycle (job-10 < job-2); census must track Job status exactly."""
    job = _job(min_r=12, max_r=16)
    plan = JobParser().parse_to_workers(job)
    cluster.create_worker_group(plan)
    server.reconcile_pods()
    assert cluster.job_pods(job)[0] == 12

    group = cluster.get_worker_group(job)
    group.parallelism = 10
    cluster.update_worker_group(group)
    server.reconcile_pods()
    assert cluster.job_pods(job)[0] == 10

    group = cluster.get_worker_group(job)
    group.parallelism = 12
    cluster.update_worker_group(group)
    server.reconcile_pods()
    total, running, _ = cluster.job_pods(job)
    assert (total, running) == (12, 12)


def test_training_job_source_and_status(server, cluster):
    server.create_training_job(
        {
            "apiVersion": "edl-tpu.org/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "tj1", "namespace": "default"},
            "spec": {
                "fault_tolerant": True,
                "worker": {"min_replicas": 1, "max_replicas": 4,
                           "entrypoint": "python t.py"},
            },
        }
    )
    jobs = cluster.list_training_jobs()
    assert [j.name for j in jobs] == ["tj1"]
    assert jobs[0].spec.worker.max_replicas == 4

    jobs[0].status.phase = JobPhase.RUNNING
    jobs[0].status.parallelism = 3
    cluster.update_training_job_status(jobs[0])
    obj = server.get_object("edl-tpu.org/v1", "trainingjobs", "default", "tj1")
    assert obj["status"]["phase"] == "running"
    assert obj["status"]["parallelism"] == 3


def test_job_source_diffs_events(server, cluster):
    # watch=False: this test pins the pure poll-diff fallback semantics
    src = KubeJobSource(cluster, watch=False)
    events = []
    cb = lambda kind: lambda j: events.append((kind, j.name))  # noqa: E731

    server.create_training_job(
        {"metadata": {"name": "a", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == [("add", "a")]

    # spec change -> update
    obj = server.get_object("edl-tpu.org/v1", "trainingjobs", "default", "a")
    obj["spec"]["worker"]["max_replicas"] = 6
    server.create_training_job(obj)  # overwrite in place
    events.clear()
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == [("upd", "a")]

    server.delete_training_job("default", "a")
    events.clear()
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == [("del", "a")]


def test_cli_controller_kube_mode(server):
    """`edl controller --kube --kube-url ...` runs the same loop the
    in-cluster Deployment does (deploy/controller.yaml)."""
    from edl_tpu.cli.main import build_parser, main

    server.add_node("n0", cpu="96", memory="384Gi", tpu=8)
    server.start_reconciler()
    server.create_training_job(
        {
            "metadata": {"name": "cli", "namespace": "default"},
            "spec": {
                "fault_tolerant": True,
                "worker": {
                    "entrypoint": "python t.py",
                    "min_replicas": 1,
                    "max_replicas": 4,
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi",
                                               "tpu": 1},
                                  "limits": {"tpu": 1}},
                },
            },
        }
    )
    rc = main(
        [
            "controller", "--kube", "--kube-url", server.url,
            "--max-load-desired", "0.9", "--tick-s", "0.01",
            "--iterations", "4",
        ]
    )
    assert rc == 0
    obj = server.get_object("edl-tpu.org/v1", "trainingjobs", "default", "cli")
    assert obj["status"]["phase"] in ("creating", "running", "scaling")
    assert server.get_object("batch/v1", "jobs", "default", "cli-worker")

    # store-less non-kube invocation is a usage error, not a crash
    args = build_parser().parse_args(["controller", "--iterations", "1"])
    from edl_tpu.cli.main import run_controller

    assert run_controller(args) == 2


def test_control_plane_end_to_end_over_kube(server, cluster):
    """Submit a TrainingJob CRD -> controller creates coordinator +
    worker Job -> autoscaler scales it up into free capacity -> status
    lands on the CRD. The kube-backed version of the reference's manual
    minikube walkthrough (reference: doc/usage.md:34-118)."""
    from edl_tpu.controller.controller import Controller
    from edl_tpu.scheduler.autoscaler import Autoscaler

    for i in range(4):
        server.add_node(f"n{i}", cpu="96", memory="384Gi", tpu=8)

    server.create_training_job(
        {
            "metadata": {"name": "e2e", "namespace": "default"},
            "spec": {
                "fault_tolerant": True,
                "worker": {
                    "entrypoint": "python train.py",
                    "min_replicas": 2,
                    "max_replicas": 8,
                    "resources": {"requests": {"cpu": "2", "memory": "4Gi",
                                               "tpu": 1},
                                  "limits": {"tpu": 1}},
                },
            },
        }
    )

    controller = Controller(
        cluster, autoscaler=Autoscaler(cluster, max_load_desired=0.9)
    )
    source = KubeJobSource(cluster)
    for _ in range(6):
        source.poll(controller.on_add, controller.on_update, controller.on_delete)
        server.reconcile_pods()
        controller.autoscaler.tick()
        controller.step()
        for u in controller.updaters.values():
            cluster.update_training_job_status(u.job)

    assert controller.phase_of("e2e") in (JobPhase.RUNNING, JobPhase.SCALING)
    group = cluster.get_worker_group(_job(name="e2e"))
    assert group.parallelism == 8  # scaled to max into free chips
    # the retarget must surface as a reshard (scale_listeners hook)
    assert controller.updaters["e2e"].job.status.reshard_count >= 1

    obj = server.get_object("edl-tpu.org/v1", "trainingjobs", "default", "e2e")
    assert obj["status"]["phase"] in ("running", "scaling")
    assert obj["status"]["parallelism"] == 8

    # deletion drains children (the DELETED event rides the watch
    # stream, so tick until it lands)
    server.delete_training_job("default", "e2e")
    deadline = time.monotonic() + 10
    while server.get_object("batch/v1", "jobs", "default", "e2e-worker"):
        source.poll(controller.on_add, controller.on_update, controller.on_delete)
        if time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert server.get_object("batch/v1", "jobs", "default", "e2e-worker") is None
    assert (
        server.get_object("apps/v1", "deployments", "default", "e2e-coordinator")
        is None
    )


def test_job_source_keeps_unparseable_job(server, cluster):
    """A CR that stops parsing (bad kubectl edit, schema drift) must not
    be diffed as a deletion — that would tear down the live job."""
    src = KubeJobSource(cluster, watch=False)
    events = []
    cb = lambda kind: lambda j: events.append((kind, j.name))  # noqa: E731

    good = {
        "metadata": {"name": "a", "namespace": "default"},
        "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}},
    }
    server.create_training_job(good)
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == [("add", "a")]

    broken = {
        "metadata": {"name": "a", "namespace": "default"},
        "spec": {
            "worker": {
                "min_replicas": 1,
                "max_replicas": 2,
                "resources": {"requests": {"cpu": "not-a-number"}},
            }
        },
    }
    server.create_training_job(broken)  # overwrite in place
    events.clear()
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == []  # neither delete nor update

    server.create_training_job(good)  # repaired
    events.clear()
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == []  # same spec as last good state

    server.delete_training_job("default", "a")
    events.clear()
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == [("del", "a")]  # a real deletion still fires


def test_controller_step_isolates_failing_updater(cluster):
    """One persistently failing updater must not starve the others
    (reference runs each updater in its own goroutine,
    trainingJobUpdater.go:74)."""
    from edl_tpu.controller.controller import Controller

    ctl = Controller(cluster)
    ctl.on_add(_job("good"))
    ctl.on_add(_job("bad"))

    calls = []
    ctl.updaters["good"].step = lambda: calls.append("good")

    def _boom():
        calls.append("bad")
        raise RuntimeError("create failed: 422")

    ctl.updaters["bad"].step = _boom
    ctl.step()  # must not raise
    assert calls.count("bad") == 1 and calls.count("good") == 1


def test_same_name_jobs_in_two_namespaces_do_not_collide(server, cluster):
    from edl_tpu.controller.controller import Controller

    ctl = Controller(cluster)
    src = KubeJobSource(cluster, watch=False)
    for ns in ("team-a", "team-b"):
        server.create_training_job(
            {
                "metadata": {"name": "train", "namespace": ns},
                "spec": {
                    "fault_tolerant": True,
                    "worker": {
                        "min_replicas": 1,
                        "max_replicas": 2,
                        "entrypoint": "python t.py",
                    },
                },
            }
        )
    src.poll(ctl.on_add, ctl.on_update, ctl.on_delete)
    assert set(ctl.updaters) == {"team-a/train", "team-b/train"}
    assert len(ctl.autoscaler._events.queue) == 2

    # deleting one namespace's job leaves the other reconciled
    server.delete_training_job("team-a", "train")
    src.poll(ctl.on_add, ctl.on_update, ctl.on_delete)
    assert set(ctl.updaters) == {"team-b/train"}


def test_coordinator_create_repairs_missing_service(server, cluster):
    """A create that died between the Deployment and Service POSTs is
    repaired by the updater's get-or-create on the next tick."""
    parser = JobParser()
    job = _job("demo")
    parser.validate(job)
    plan = parser.parse_to_coordinator(job)
    cluster.create_coordinator(plan)
    # simulate the torn create: service never landed
    cluster.api.delete(f"/api/v1/namespaces/default/services/{plan.name}")
    got = cluster.get_coordinator("default", plan.name)
    assert got.endpoint.endswith(":0")  # detectably broken

    repaired = cluster.create_coordinator(plan)  # 409 on Deployment is OK
    assert not repaired.endpoint.endswith(":0")
    assert not cluster.get_coordinator("default", plan.name).endpoint.endswith(":0")


# -- streaming watch (VERDICT r2 Missing #4) --------------------------------


def _poll_until(src, events, want, timeout_s=10.0):
    """Tick the source until `want(events)` holds (watch events arrive
    asynchronously, unlike the synchronous poll-diff mode)."""
    cb = lambda kind: lambda j: events.append((kind, j.name))  # noqa: E731
    deadline = time.monotonic() + timeout_s
    while True:
        src.poll(cb("add"), cb("upd"), cb("del"))
        if want(events):
            return
        if time.monotonic() > deadline:
            raise TimeoutError(events)
        time.sleep(0.05)


def test_watch_streams_events_without_relisting(server, cluster):
    """Steady state costs ZERO list calls: adds/updates/deletes arrive
    over the streaming watch connection (informer semantics, reference
    pkg/controller.go:79-108)."""
    src = KubeJobSource(cluster, watch=True)
    events = []
    src.poll(lambda j: None, lambda j: None, lambda j: None)  # relist + start
    lists_after_start = server.list_count()

    server.create_training_job(
        {"metadata": {"name": "a", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    _poll_until(src, events, lambda e: ("add", "a") in e)

    obj = server.get_object("edl-tpu.org/v1", "trainingjobs", "default", "a")
    obj["spec"]["worker"]["max_replicas"] = 6
    server.create_training_job(obj)  # overwrite -> MODIFIED event
    _poll_until(src, events, lambda e: ("upd", "a") in e)

    server.delete_training_job("default", "a")
    _poll_until(src, events, lambda e: ("del", "a") in e)

    # the whole add/update/delete flow rode the stream. Allow ONE
    # fallback relist (a transient stream break under CI contention is
    # correct fallback behavior, not a failure) — the point is the
    # steady state is not O(ticks) lists.
    assert server.list_count() <= lists_after_start + 1
    src.close()


def test_watch_resumes_after_stream_window_closes(server, cluster):
    """The server closes each watch window after timeoutSeconds; the
    client re-watches from its last resourceVersion and misses nothing."""
    src = KubeJobSource(cluster, watch=True, watch_timeout_s=1.0)
    events = []
    src.poll(lambda j: None, lambda j: None, lambda j: None)
    time.sleep(1.6)  # at least one window expiry + re-watch
    server.create_training_job(
        {"metadata": {"name": "late", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    _poll_until(src, events, lambda e: ("add", "late") in e)
    src.close()


def test_watch_falls_back_to_list_diff_when_stream_dies(server, cluster):
    """A dead watch thread is not a dead source: the next poll relists
    (full diff) and restarts the stream."""
    src = KubeJobSource(cluster, watch=True)
    events = []
    src.poll(lambda j: None, lambda j: None, lambda j: None)
    # kill the stream from the client side (simulates apiserver drop);
    # close() interrupts the blocked read so this is bounded, not a
    # wait-out of the watch window
    src.close()
    deadline = time.monotonic() + 5
    while src._watch_healthy():
        assert time.monotonic() < deadline, "watch thread failed to exit"
        time.sleep(0.02)
    src._stop = False

    server.create_training_job(
        {"metadata": {"name": "b", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    # first poll after death relists -> synchronous add, watch restarts
    cb = lambda kind: lambda j: events.append((kind, j.name))  # noqa: E731
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert ("add", "b") in events
    assert src._watch_healthy()
    src.close()


def test_watch_bookmarks_advance_resume_point(server, cluster):
    """BOOKMARK events (k8s allowWatchBookmarks) advance the client's
    resume rv through quiet periods WITHOUT being queued as object
    events, so a later reconnect resumes from a fresh rv instead of
    replaying (or 410ing on) history."""
    src = KubeJobSource(cluster, watch=True)
    events = []
    src.poll(lambda j: None, lambda j: None, lambda j: None)
    rv0 = int(src._rv or 0)
    # unrelated mutations (pods) bump the server head; the trainingjob
    # watch sees no object events, only bookmarks. record() requires
    # the journal lock (its contract; the handlers' snapshots depend
    # on rv-increment and append being atomic).
    with server.state.lock:
        for i in range(3):
            server.state.record(
                ("v1", "pods"), "default", f"p{i}", "ADDED",
                {"metadata": {"name": f"p{i}", "namespace": "default"}},
            )
    deadline = time.monotonic() + 5
    while int(src._rv or 0) <= rv0:
        assert time.monotonic() < deadline, (src._rv, rv0)
        time.sleep(0.05)
    # no spurious object events leaked through
    cb = lambda kind: lambda j: events.append((kind, j.name))  # noqa: E731
    src.poll(cb("add"), cb("upd"), cb("del"))
    assert events == []
    # and a REAL event after the bookmarks still arrives
    server.create_training_job(
        {"metadata": {"name": "afterbm", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    _poll_until(src, events, lambda e: ("add", "afterbm") in e)
    src.close()


def test_watch_410_error_event_on_compacted_resume(server, cluster):
    """The fake apiserver honors etcd-compaction semantics: a watch
    resuming from an rv older than the (compacted) journal head gets a
    410 Gone ERROR event as its first event — the k8s contract the
    client's recovery path is written against."""
    server.create_training_job(
        {"metadata": {"name": "old", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    server.state.compact_events(keep_last=0)
    server.create_training_job(
        {"metadata": {"name": "new", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    evs = list(
        cluster.api.watch(
            cluster.training_job_list_path(""), resource_version="1",
            timeout_s=1.0,
        )
    )
    real = [e for e in evs if e.get("type") not in ("SYNC", "HEARTBEAT")]
    assert real and real[0]["type"] == "ERROR", evs
    assert real[0]["object"]["code"] == 410


def test_watch_recovers_from_mid_stream_410(server, cluster):
    """A watch resuming from a compacted rv gets 410 Gone mid-stream:
    the ERROR event must TERMINATE the watch loop (not hang, not be
    applied as an object event), and the next poll RELISTS — observing
    every change across the gap, missing nothing — then restarts a
    healthy stream (informer semantics, reference
    pkg/controller.go:79-108). The loop is driven synchronously so the
    410 path is exercised deterministically, not by racing the open
    window against the compaction."""
    src = KubeJobSource(cluster, watch=True, watch_timeout_s=1.0)
    events = []
    server.create_training_job(
        {"metadata": {"name": "during-gap", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    server.state.compact_events(keep_last=0)
    server.create_training_job(
        {"metadata": {"name": "post-compact", "namespace": "default"},
         "spec": {"worker": {"min_replicas": 1, "max_replicas": 2}}}
    )
    # a client that slept through the compaction: resume point far
    # behind the journal head. Run ONE watch loop synchronously — the
    # server answers 410, the loop must return via the ERROR path
    # within the first window rather than stream or hang.
    with src._lock:
        src._rv = "1"
    t0 = time.monotonic()
    src._watch_loop()
    assert time.monotonic() - t0 < 10, "watch loop hung on the 410"
    assert not src._watch_healthy()
    # no half-applied events from the dead stream
    with src._lock:
        assert all(e.get("type") != "ERROR" for e in src._events)

    # recovery: relist surfaces BOTH jobs (nothing missed across the
    # gap) and the stream comes back healthy
    _poll_until(
        src, events,
        lambda e: ("add", "during-gap") in e and ("add", "post-compact") in e,
    )
    assert src._watch_healthy()
    src.close()


def test_spec_env_rendered_into_worker_pods(server, cluster):
    """spec.env rides into the worker Job's container env (underneath
    the derived contract) — how a cluster job turns on EDL_INT8_MXU,
    picks EDL_MODEL, etc."""
    job = _job(name="enveee")
    job.spec.env = {"EDL_MODEL": "llama", "EDL_INT8_MXU": "1"}
    cluster.create_worker_group(JobParser().parse_to_workers(job))
    obj = server.get_object("batch/v1", "jobs", "default", "enveee-worker")
    env = {
        e["name"]: e["value"]
        for e in obj["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    assert env["EDL_MODEL"] == "llama"
    assert env["EDL_INT8_MXU"] == "1"
    assert env["EDL_JOB_NAME"] == "enveee"  # contract still present
