"""Pallas flash attention vs the reference oracle (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.parallel.ring_attention import reference_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 128, 2, 32
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gqa_no_repeat():
    # grouped KV heads (H=4, KV=2) must match the repeated-KV oracle
    rng = np.random.RandomState(1)
    b, t, h, kv, d = 2, 64, 4, 2, 16
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, kv, d).astype(np.float32))
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    kr = jnp.repeat(k, h // kv, axis=2)
    vr = jnp.repeat(v, h // kv, axis=2)
    ref = reference_attention(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_rejects_ragged_seq():
    from edl_tpu.ops.flash_attention import flash_supported

    q = jnp.zeros((1, 100, 1, 16))
    with pytest.raises(ValueError):
        flash_attention(q, q, q, block_q=64, block_k=64, interpret=True)
    # blocks step DOWN to the largest power-of-two divisor >= 128, so
    # any multiple of 128 is supported (640 -> blocks of 128; 1536 ->
    # block_k 512); only non-multiples of 128 are rejected
    assert flash_supported(640)
    assert flash_supported(1536)
    assert flash_supported(384)  # block_k clamps to 384
    assert flash_supported(2048)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grad_matches_reference(causal):
    import jax

    rng = np.random.RandomState(2)
    b, t, h, d = 2, 96, 2, 32
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    w = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                            interpret=True)
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_grad_gqa_group_sum():
    """GQA backward: dK/dV must sum the per-query-head contributions
    into the shared kv heads — checked against the repeated-KV oracle."""
    import jax

    rng = np.random.RandomState(3)
    b, t, h, kv, d = 1, 64, 4, 2, 16
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, kv, d).astype(np.float32))
    w = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, h // kv, axis=2)
        vr = jnp.repeat(v, h // kv, axis=2)
        return jnp.sum(reference_attention(q, kr, vr, causal=True) * w)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=5e-4,
            err_msg=f"d{name} mismatch",
        )
