"""Native (C++) planner ≡ Python planner, cross-checked on random fleets.

The native core (native/scheduler/sched.cc) must produce the exact plan
the Python dry-run fixed point produces — same fulfillment sort, same
up/down passes, same host first-fit — for both built-in slice policies.
"""

import numpy as np
import pytest

from edl_tpu.api.job import TrainingJob
from edl_tpu.cluster import topology
from edl_tpu.cluster.fake import FakeCluster, FakeHost
from edl_tpu.cluster.resource import ClusterResource, Hosts
from edl_tpu.controller.controller import Controller
from edl_tpu.scheduler import native as native_sched
from edl_tpu.scheduler.autoscaler import (
    Autoscaler,
    JobState,
    scale_all_jobs_dry_run,
)

pytestmark = pytest.mark.skipif(
    not native_sched.available(), reason="no C++ toolchain"
)


class _Group:
    def __init__(self, parallelism):
        self.parallelism = parallelism


def _mk_job(name, lo, hi, cur, chips, cpu, mem, accelerator=""):
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                **(
                    {"accelerator_type": accelerator} if accelerator else {}
                ),
                "worker": {
                    "min_replicas": lo,
                    "max_replicas": hi,
                    "resources": {
                        "requests": {"cpu": f"{cpu}m", "memory": f"{mem}M"},
                        "limits": {"tpu": chips},
                    },
                },
            },
        }
    )
    js = JobState(config=job)
    js.group = _Group(cur)
    return js


def _mk_resource(rng, n_hosts, with_blocks=False):
    hosts = Hosts(
        cpu_idle_milli={}, mem_free_mega={}, chips_free={}
    )
    r = ClusterResource()
    for i in range(n_hosts):
        name = f"h{i:02d}"
        cpu = int(rng.choice([8000, 16000, 32000]))
        mem = int(rng.choice([16000, 32000]))
        chips = int(rng.choice([0, 4, 8]))
        hosts.cpu_idle_milli[name] = cpu
        hosts.mem_free_mega[name] = mem
        hosts.chips_free[name] = chips
        if with_blocks and chips > 0 and rng.rand() < 0.8:
            hosts.ici_block[name] = f"pod{i // 4}"
            hosts.ici_index[name] = i % 4
        r.cpu_total_milli += cpu
        r.mem_total_mega += mem
        r.chip_total += chips
    r.hosts = hosts
    return r


@pytest.mark.parametrize("policy_name", ["flexible", "pow2", "auto"])
@pytest.mark.parametrize("seed", range(20))
def test_native_plan_matches_python(seed, policy_name):
    from edl_tpu.scheduler.autoscaler import resolve_policy

    rng = np.random.RandomState(seed)
    policy = (
        "auto" if policy_name == "auto" else topology.POLICIES[policy_name]
    )
    n_jobs = int(rng.randint(1, 6))
    jobs = []
    for i in range(n_jobs):
        lo = int(rng.randint(0, 4))
        hi = lo + int(rng.randint(0, 8))
        cur = int(rng.randint(0, hi + 2))
        chips = int(rng.choice([0, 1, 2, 4]))
        cpu = int(rng.choice([500, 1000, 4000]))
        mem = int(rng.choice([100, 1000, 4000]))
        accel = (
            str(rng.choice(["v5e", "v4", "cpu", ""]))
            if policy_name == "auto"
            else ""
        )
        jobs.append(_mk_job(f"job{i}", lo, hi, cur, chips, cpu, mem, accel))

    r = _mk_resource(
        rng, int(rng.randint(1, 8)), with_blocks=(policy_name == "auto")
    )
    # book the current usage so totals are consistent-ish
    for j in jobs:
        cur = j.group.parallelism
        r.chip_limit += j.chips_per_worker() * cur
        r.cpu_request_milli += j.cpu_request_milli() * cur
        r.mem_request_mega += j.mem_request_mega() * cur

    max_load = float(rng.choice([0.8, 0.9, 0.97, 1.0]))

    py = scale_all_jobs_dry_run(jobs, r.copy(), max_load, policy)
    nat = native_sched.plan_native(
        jobs, r, max_load, [resolve_policy(policy, j) for j in jobs]
    )
    assert nat is not None
    # python dict contains elastic candidates it touched; native has all
    for name in nat:
        assert nat[name] == py.get(name, 0), (
            f"seed={seed} policy={policy_name} job={name}: "
            f"native={nat[name]} python={py.get(name, 0)} (full: {nat} vs {py})"
        )


def test_autoscaler_tick_native_matches_python():
    def build():
        cluster = FakeCluster(
            hosts=[FakeHost(f"h{i}", 16000, 32000, 4) for i in range(4)]
        )
        return cluster

    def run(use_native):
        cluster = build()
        ctl = Controller(
            cluster,
            autoscaler=Autoscaler(cluster, max_load_desired=1.0,
                                  use_native=use_native),
        )
        job = TrainingJob.from_dict(
            {
                "metadata": {"name": "j"},
                "spec": {
                    "fault_tolerant": True,
                    "worker": {
                        "min_replicas": 2,
                        "max_replicas": 8,
                        "resources": {
                            "requests": {"cpu": "1000m", "memory": "1Gi"},
                            "limits": {"tpu": 2},
                        },
                    },
                },
            }
        )
        cluster.submit_job(job)
        ctl.step()
        targets = []
        for _ in range(4):
            cluster.reconcile()
            targets.append(dict(ctl.autoscaler.tick()))
            ctl.step()
        return targets

    assert run(True) == run(False)
