"""Robustness satellites (ISSUE 4): heartbeat thread survives
coordinator loss with a degraded gauge, p2p restore times out instead
of hanging when the decision never arrives, MetricsPusher backs off
with jitter, and injected RPC drops ride the real reconnect path."""

import time
from types import SimpleNamespace

import pytest

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.runtime.coordinator import PyCoordinator, ensure_native_built
from edl_tpu.utils import faults

HAVE_NATIVE = ensure_native_built()


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


# -- worker heartbeat degradation -------------------------------------------


def _bare_worker():
    """An ElasticWorker shell with just the state _beat_tick touches —
    the real __init__ dials a coordinator, which these tests replace."""
    from edl_tpu.runtime.worker_main import ElasticWorker

    w = object.__new__(ElasticWorker)
    w.cfg = SimpleNamespace(
        coord_host="127.0.0.1", coord_port=1, worker_id="w0",
        member_ttl_s=2.0,
    )
    w._leaving = False
    w._hb_degraded = False
    return w


def test_heartbeat_tick_survives_dead_coordinator():
    """A ConnectionError (reconnect window exhausted / nothing
    listening) must NOT propagate out of the beat tick: the worker
    flips the degraded flag + gauge and keeps retrying — previously the
    thread died and the worker silently TTL-expired while training."""
    reg = obs_metrics.reset_default_registry()
    w = _bare_worker()  # port 1: nothing listens
    for _ in range(3):  # repeated ticks keep retrying, never raise
        assert w._beat_tick(None, incarnation=1) is None
    assert w._hb_degraded
    g = reg.get("edl_worker_heartbeat_degraded")
    assert g is not None and g.value() == 1


def test_heartbeat_tick_recovers_and_clears_gauge():
    reg = obs_metrics.reset_default_registry()
    w = _bare_worker()
    assert w._beat_tick(None, incarnation=1) is None  # outage
    assert w._hb_degraded

    class FakeClient:
        def __init__(self):
            self.beats = 0

        def heartbeat(self, wid):
            self.beats += 1
            return True

        def close(self):
            pass

    c = FakeClient()
    assert w._beat_tick(c, incarnation=1) is c  # coordinator back
    assert c.beats == 1
    assert not w._hb_degraded
    assert reg.get("edl_worker_heartbeat_degraded").value() == 0


def test_heartbeat_tick_reregisters_after_ttl_eviction():
    w = _bare_worker()

    class EvictedClient:
        def __init__(self):
            self.registered = []

        def heartbeat(self, wid):
            return False  # TTL already evicted us

        def register(self, wid, inc):
            self.registered.append((wid, inc))
            return 7

        def close(self):
            pass

    c = EvictedClient()
    assert w._beat_tick(c, incarnation=4) is c
    assert c.registered == [("w0", 4)]


# -- p2p restore: no decision must raise, not hang ---------------------------


def _plane(cl, timeout_s):
    from edl_tpu.runtime.epoch_gc import EpochKeyGC
    from edl_tpu.runtime.p2p_restore import P2PRestorePlane

    cfg = SimpleNamespace(
        job="j", worker_id="w1", p2p=True, rendezvous_timeout_s=timeout_s,
        p2p_linger_s=0.0,
    )
    return P2PRestorePlane(
        cfg, lambda *p: "/".join(("j",) + p), EpochKeyGC(), lambda: None
    )


def test_p2p_restore_times_out_when_rank0_never_decides():
    """Rank 0 is ALIVE but never publishes the restore decision (e.g.
    wedged probing peers): a non-leader must raise TimeoutError within
    rendezvous_timeout_s — never hang the epoch."""
    cl = PyCoordinator(member_ttl_s=30.0)
    cl.register("w0", 1)
    cl.register("w1", 1)
    members = cl.members()
    assert members[0].rank == 0 and members[0].name == "w0"
    plane = _plane(cl, timeout_s=0.4)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="no restore decision"):
        plane.restore(
            cl, cl.epoch(), rank=1, members=members, like=None,
            state_sh=None, manifest=None, ram_snapshot=None,
        )
    elapsed = time.monotonic() - t0
    assert 0.3 <= elapsed < 5.0, elapsed


def test_p2p_restore_bails_fast_when_rank0_dead():
    """A DEAD rank 0 can never publish: the waiter must bail with
    RuntimeError immediately, not burn the rendezvous timeout."""
    cl = PyCoordinator(member_ttl_s=30.0)
    cl.register("w0", 1)
    cl.register("w1", 1)
    members = cl.members()
    epoch = cl.epoch()
    cl.leave("w0")  # rank 0 dies after rendezvous
    plane = _plane(cl, timeout_s=30.0)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="rank-0 worker died"):
        plane.restore(
            cl, epoch, rank=1, members=members, like=None,
            state_sh=None, manifest=None, ram_snapshot=None,
        )
    assert time.monotonic() - t0 < 5.0


def test_p2p_restore_bails_on_epoch_move():
    cl = PyCoordinator(member_ttl_s=30.0)
    cl.register("w0", 1)
    cl.register("w1", 1)
    members = cl.members()
    epoch = cl.epoch()
    cl.register("w2", 1)  # membership moves: the group is regrouping
    plane = _plane(cl, timeout_s=30.0)
    with pytest.raises(RuntimeError, match="membership moved"):
        plane.restore(
            cl, epoch, rank=1, members=members, like=None,
            state_sh=None, manifest=None, ram_snapshot=None,
        )


# -- MetricsPusher backoff ---------------------------------------------------


def test_pusher_backoff_grows_and_resets():
    from edl_tpu.obs.fleet import MetricsPusher

    reg = obs_metrics.reset_default_registry()
    fail = {"on": True}

    def publish(payload):
        if fail["on"]:
            raise ConnectionError("outage")

    p = MetricsPusher(publish, interval_s=1.0, backoff_cap_s=64.0)
    assert p.next_wait_s() == 1.0  # healthy: the fixed interval
    waits = []
    for _ in range(4):
        assert not p.push_once()
        waits.append(p.next_wait_s())
    # exponential with ±50% jitter: streak k waits in [2^k/2, 1.5*2^k];
    # adjacent streaks may overlap, two apart may not
    for k, w in enumerate(waits, start=1):
        assert 0.5 * 2**k <= w <= 1.5 * 2**k, (k, w)
    assert waits[2] > waits[0] and waits[3] > waits[1]
    c = reg.get("edl_metrics_push_failures_total")
    assert c is not None and c.value() == 4
    fail["on"] = False
    assert p.push_once()  # success resets the streak...
    assert p.next_wait_s() == 1.0  # ...back to full rate
    assert c.value() == 4


def test_pusher_backoff_respects_cap():
    from edl_tpu.obs.fleet import MetricsPusher

    obs_metrics.reset_default_registry()
    p = MetricsPusher(
        lambda s: (_ for _ in ()).throw(OSError("down")),
        interval_s=1.0, backoff_cap_s=8.0,
    )
    for _ in range(20):
        p.push_once()
    assert p.next_wait_s() <= 1.5 * 8.0


def test_pusher_failure_site_injectable():
    """The metrics.push fault point drives the REAL failure path: the
    counter increments and backoff engages without a broken network."""
    from edl_tpu.obs.fleet import MetricsPusher

    obs_metrics.reset_default_registry()
    got = []
    p = MetricsPusher(got.append, interval_s=1.0)
    faults.arm("metrics.push:raise@n=1")
    assert not p.push_once()  # injected
    assert p.next_wait_s() != 1.0
    assert p.push_once()  # next tick succeeds, snapshot delivered
    assert len(got) == 1 and p.next_wait_s() == 1.0


# -- injected RPC drops ride the real reconnect path -------------------------


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_client_survives_injected_rpc_drops():
    """coord.rpc:drop raises ConnectionError INSIDE _call: the client
    must close, re-dial, and re-issue transparently — every op
    succeeds despite a 30% drop rate, and the reconnect counter shows
    the path actually ran."""
    from edl_tpu.runtime.coordinator import CoordinatorServer

    reg = obs_metrics.reset_default_registry()
    with CoordinatorServer(member_ttl_s=5.0) as srv:
        c = srv.client()
        faults.arm("coord.rpc:drop@p=0.3", seed=1)
        for i in range(30):
            c.kv_put(f"k{i}", str(i))
        for i in range(30):
            assert c.kv_get(f"k{i}") == str(i)
        fired = faults.counts()["coord.rpc"]
        faults.disarm()
        c.close()
    assert fired > 0
    rec = reg.get("edl_coordinator_reconnects_total")
    assert rec is not None and rec.value() >= fired


# -- the chaos harness CLI lane (slow) ---------------------------------------


@pytest.mark.slow
def test_exp_chaos_dryrun():
    import os
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "scripts/exp_chaos.py", "--dryrun"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "serving lane OK" in out.stdout
    assert "chaos soak OK" in out.stdout
