"""Real-row weighting at the ragged dataset tail (VERDICT r2 Weak #5).

The elastic runtime pads tail tasks (wrap-repeat) and replays previous
batches to keep SPMD shapes aligned; those filler rows must contribute
ZERO gradient. The oracle here is the sequential gradient over the real
rows alone.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import optax

from edl_tpu.models import linreg, llama
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime.launcher import ProcessJobLauncher
from edl_tpu.runtime.worker_main import ElasticWorker
from edl_tpu.train.trainer import (
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def test_padded_rows_contribute_zero_gradient(cpu_devices):
    """A worker-style padded+replayed global batch produces EXACTLY the
    gradient of the real rows — checked against jax.grad on the real
    subset."""
    rng = np.random.RandomState(0)
    params = linreg.init_params(jax.random.PRNGKey(0))
    real = 5  # ragged tail: 5 real rows in a 16-row global batch
    x = rng.randn(16, linreg.N_FEATURES).astype(np.float32)
    y = rng.randn(16, 1).astype(np.float32)
    w = np.zeros(16, np.float32)
    w[:real] = 1.0
    batch = {"x": x, "y": y, "_w": w}

    g_weighted = jax.grad(linreg.loss_fn)(params, batch)
    g_oracle = jax.grad(linreg.loss_fn)(
        params, {"x": x[:real], "y": y[:real]}
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g_weighted,
        g_oracle,
    )


def test_all_replay_step_is_a_noop(cpu_devices):
    """Every peer replaying (queue drained mid-epoch): weights all zero
    -> loss 0, zero gradients, params unchanged — not NaNs."""
    params = linreg.init_params(jax.random.PRNGKey(0))
    batch = {
        "x": np.ones((8, linreg.N_FEATURES), np.float32),
        "y": np.ones((8, 1), np.float32),
        "_w": np.zeros(8, np.float32),
    }
    loss = linreg.loss_fn(params, batch)
    grads = jax.grad(linreg.loss_fn)(params, batch)
    assert float(loss) == 0.0
    assert all(
        float(jnp.sum(jnp.abs(g))) == 0.0
        for g in jax.tree_util.tree_leaves(grads)
    )


def test_sharded_train_step_matches_sequential_oracle(cpu_devices):
    """Full jit train step on a dp mesh with a ragged tail: the final
    params equal a sequential (single-device, real-rows-only) SGD."""
    rng = np.random.RandomState(1)
    lr = 0.1
    plan = MeshPlan.data_parallel(8)
    mesh = plan.build()
    params = linreg.init_params(jax.random.PRNGKey(2))
    tx = optax.sgd(lr)
    state = shard_state(TrainState.create(params, tx), plan, mesh, None)
    step = make_train_step(linreg.loss_fn, tx, plan, mesh)

    # host copies: the jit step donates its state, which may alias the
    # original param buffers
    seq_params = jax.tree_util.tree_map(np.asarray, params)
    for n_real in (16, 16, 6):  # last step: ragged tail of 6 real rows
        x = rng.randn(16, linreg.N_FEATURES).astype(np.float32)
        y = rng.randn(16, 1).astype(np.float32)
        w = np.zeros(16, np.float32)
        w[:n_real] = 1.0
        x[n_real:] = x[:1]  # filler = wrap-padding, as the runtime does
        y[n_real:] = y[:1]
        state, _ = step(
            state, global_batch({"x": x, "y": y, "_w": w}, plan, mesh)
        )
        g = jax.grad(linreg.loss_fn)(
            seq_params, {"x": x[:n_real], "y": y[:n_real]}
        )
        seq_params = jax.tree_util.tree_map(
            lambda p, gg: p - lr * gg, seq_params, g
        )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        jax.device_get(state.params),
        seq_params,
    )


def test_llama_weighted_loss_matches_real_rows(cpu_devices):
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = llama.synthetic_tokens(np.random.RandomState(0), 8, 16, cfg.vocab)
    loss_fn = llama.make_loss_fn(cfg)
    w = np.zeros(8, np.float32)
    w[:3] = 1.0
    weighted = loss_fn(params, {"tokens": toks["tokens"], "_w": w})
    real_only = loss_fn(params, {"tokens": toks["tokens"][:3]})
    np.testing.assert_allclose(
        float(weighted), float(real_only), rtol=1e-5, atol=1e-6
    )


def test_worker_local_batch_weights(tmp_path):
    """The worker's lease/replay/zero paths attach the right weights."""
    from edl_tpu.runtime.coordinator import PyCoordinator

    class Cfg:
        worker_id = "w0"
        n_samples = 40

    w = ElasticWorker.__new__(ElasticWorker)
    w.cfg = Cfg()
    w._local_rows = 16
    w._last_local = None
    cl = PyCoordinator()
    cl.queue_init(40, 16, passes=1)  # tasks: 16, 16, 8 (ragged tail)

    def batch_fn(s, e):
        return {"x": np.arange(s, e, dtype=np.float32)[:, None]}

    b1, t1 = w._local_batch(cl, batch_fn)
    assert b1["_w"].sum() == 16
    cl.ack(t1)
    b2, t2 = w._local_batch(cl, batch_fn)
    cl.ack(t2)
    b3, t3 = w._local_batch(cl, batch_fn)  # the 8-row tail, padded to 16
    assert t3 is not None and b3["_w"].sum() == 8
    assert b3["x"].shape[0] == 16  # SPMD shape kept
    cl.ack(t3)
    b4, t4 = w._local_batch(cl, batch_fn)  # queue empty: replay, weight 0
    assert t4 is None and b4["_w"].sum() == 0


@pytest.mark.multiproc  # real worker subprocesses, live timing
def test_multiproc_ragged_tail_trains(tmp_path):
    """Process-runtime e2e on a dataset whose size does NOT divide the
    chunk grid: completes with exact accounting and a decreasing loss."""
    with ProcessJobLauncher(
        job="mptail",
        model="linreg",
        min_workers=2,
        max_workers=2,
        n_samples=1000,  # 1000 % (32*2) != 0 — ragged tail guaranteed
        passes=1,
        per_device_batch=32,
        work_dir=str(tmp_path),
    ) as launcher:
        launcher.start(2)
        rcs = launcher.wait(timeout_s=180)
        assert all(rc == 0 for rc in rcs.values()), (
            rcs,
            {w: launcher.log_tail(w) for w in rcs},
        )
        assert launcher.kv("phase") == "succeeded"
        stats = launcher.client.queue_stats()
        assert stats["done"] == -(-1000 // 32)  # ceil: tail task acked once
        assert float(launcher.kv("loss_last")) < float(launcher.kv("loss_first"))
