"""Parallel core: mesh plans, sharding rules, jit train steps on the
8-device virtual CPU mesh (the multi-host TPU stand-in, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.api.job import MeshSpec
from edl_tpu.models import ctr, linreg
from edl_tpu.parallel import sharding as shd
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import TrainState, global_batch, make_train_step, shard_state


def test_mesh_plan_factorization(cpu_devices):
    plan = MeshPlan.create(dp=2, fsdp=4)
    assert plan.size() == 8
    assert plan.names == ("dp", "fsdp")
    mesh = plan.build()
    assert mesh.shape == {"dp": 2, "fsdp": 4}
    assert plan.batch_pspec() == P(("dp", "fsdp"))


def test_mesh_from_spec_completes_dp(cpu_devices):
    plan = MeshPlan.from_spec(MeshSpec(fsdp=4), 8)
    assert plan.describe() == {"dp": 2, "fsdp": 4}
    with pytest.raises(ValueError):
        MeshPlan.from_spec(MeshSpec(tp=3), 8)


def test_mesh_parse_grammar():
    """EDL_MESH strings: bare axis = growth (absorbs the elastic device
    count), axis=K pins, remainder defaults to dp."""
    assert MeshPlan.parse("dp", 8).describe() == {"dp": 8}
    assert MeshPlan.parse("fsdp", 6).describe() == {"fsdp": 6}
    assert MeshPlan.parse("fsdp,tp=2", 8).describe() == {"fsdp": 4, "tp": 2}
    assert MeshPlan.parse("fsdp=2,tp=2", 8).describe() == {
        "dp": 2,
        "fsdp": 2,
        "tp": 2,
    }
    assert MeshPlan.parse("", 4).describe() == {"dp": 4}
    with pytest.raises(ValueError):
        MeshPlan.parse("fsdp,tp=3", 8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshPlan.parse("warp=2", 4)  # unknown axis
    with pytest.raises(ValueError):
        MeshPlan.parse("tp,tp=2", 8)  # growth axis also pinned


def test_mesh_spec_growth_roundtrip():
    from edl_tpu.api.job import TrainingJob

    spec = MeshSpec(fsdp=0, tp=2, growth="fsdp")
    assert spec.to_mesh_string() == "fsdp,tp=2"
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": "j"},
            "spec": {"mesh": {"tp": 2, "growth": "fsdp"}},
        }
    )
    assert job.spec.mesh.growth == "fsdp"
    assert job.spec.mesh.to_mesh_string() == "fsdp,tp=2"
    assert job.to_dict()["spec"]["mesh"] == {"tp": 2, "growth": "fsdp"}
    with pytest.raises(ValueError, match="growth"):
        TrainingJob.from_dict(
            {"metadata": {"name": "j"}, "spec": {"mesh": {"growth": "warp"}}}
        )


def test_fsdp_pspec_picks_divisible_dim():
    assert shd.fsdp_pspec((16, 7), 8) == P("fsdp", None)
    assert shd.fsdp_pspec((7, 24), 8) == P(None, "fsdp")
    assert shd.fsdp_pspec((7,), 8) == P()  # nothing divides -> replicate
    assert shd.fsdp_pspec((64,), 1) == P()


def test_dp_training_loss_decreases(cpu_devices):
    plan = MeshPlan.data_parallel(8)
    mesh = plan.build()
    params = linreg.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    step = make_train_step(linreg.loss_fn, tx, plan, mesh)
    x, y = linreg.synthetic_dataset(1024)
    losses = []
    for i in range(20):
        lo = (i * 64) % 1024
        batch = global_batch({"x": x[lo : lo + 64], "y": y[lo : lo + 64]}, plan, mesh)
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5
    assert int(state.step) == 20


def test_fsdp_training_matches_dp(cpu_devices):
    # Same seed, same data: fsdp=8 must train to (near-)identical loss as
    # dp=8 — the sharding is a layout choice, not a math change.
    x, y = linreg.synthetic_dataset(512)

    def run(plan):
        mesh = plan.build()
        params = ctr.init_params(jax.random.PRNGKey(1), vocab=1024, emb=8)
        tx = optax.adam(1e-2)
        state = shard_state(TrainState.create(params, tx), plan, mesh)
        step = make_train_step(ctr.loss_fn, tx, plan, mesh)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(8):
            b = ctr.synthetic_batch(rng, 64, vocab=1024)
            state, m = step(state, global_batch(b, plan, mesh))
            losses.append(float(m["loss"]))
        return losses

    dp_losses = run(MeshPlan.data_parallel(8))
    fsdp_losses = run(MeshPlan.fsdp_only(8))
    np.testing.assert_allclose(dp_losses, fsdp_losses, rtol=2e-4, atol=2e-5)


def test_fsdp_actually_shards_params(cpu_devices):
    plan = MeshPlan.fsdp_only(8)
    mesh = plan.build()
    params = ctr.init_params(jax.random.PRNGKey(0), vocab=1024, emb=8)
    state = shard_state(TrainState.create(params, optax.adam(1e-3)), plan, mesh)
    emb = state.params["embedding"]
    # vocab (largest, divisible) dim sharded 8-way: each shard 1/8 rows
    shard_shapes = {s.data.shape for s in emb.addressable_shards}
    assert shard_shapes == {(128, 8)}
    # optimizer moments follow their params
    mu_emb = state.opt_state[0].mu["embedding"]
    assert {s.data.shape for s in mu_emb.addressable_shards} == {(128, 8)}


def test_ctr_learns_auc(cpu_devices):
    plan = MeshPlan.create(dp=4, fsdp=2)
    mesh = plan.build()
    params = ctr.init_params(jax.random.PRNGKey(2), vocab=4096, emb=8)
    tx = optax.adam(1e-2)
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    step = make_train_step(ctr.loss_fn, tx, plan, mesh)
    rng = np.random.RandomState(3)
    for _ in range(60):
        b = ctr.synthetic_batch(rng, 256, vocab=4096)
        state, _ = step(state, global_batch(b, plan, mesh))
    host_params = shd.to_host(state.params)
    eval_b = ctr.synthetic_batch(np.random.RandomState(99), 512, vocab=4096)
    logits = ctr.forward(host_params, eval_b["dense"], eval_b["sparse"])
    auc = float(ctr.batch_auc(jnp.asarray(logits), jnp.asarray(eval_b["label"])))
    assert auc > 0.75, f"AUC {auc} did not learn the synthetic signal"


def test_to_host_chunked_large_leaf(cpu_devices, monkeypatch):
    """Large single-device leaves stream through the chunked path and
    must land bit-identical, including a ragged final chunk."""
    monkeypatch.setattr(shd, "_CHUNK_BYTES", 1 << 10)  # force chunking
    monkeypatch.setattr(shd, "_CHUNK_WINDOW", 3)
    dev = jax.devices()[0]
    rng = np.random.RandomState(0)
    big_odd = rng.randn(1001, 7).astype(np.float32)  # ragged last chunk
    big_even = rng.randn(512, 8).astype(np.float32)
    tree = {
        "a": jax.device_put(big_odd, dev),
        "b": jax.device_put(big_even, dev),
        "small": jax.device_put(np.float32(3.5), dev),
        "none": None,
        "np_leaf": np.arange(4),
    }
    host = shd.to_host(tree)
    np.testing.assert_array_equal(host["a"], big_odd)
    np.testing.assert_array_equal(host["b"], big_even)
    assert host["small"] == np.float32(3.5)
    assert host["none"] is None
    np.testing.assert_array_equal(host["np_leaf"], np.arange(4))


def test_to_host_sharded_leaves_fetch_whole(cpu_devices, monkeypatch):
    """Sharded arrays must bypass chunking (slicing would insert
    collectives) and still round-trip exactly."""
    monkeypatch.setattr(shd, "_CHUNK_BYTES", 1 << 10)
    plan = MeshPlan.data_parallel(8)
    mesh = plan.build()
    x = np.random.RandomState(1).randn(64, 128).astype(np.float32)
    sharded = shd.shard_tree(x, mesh, P("dp"))
    assert len(sharded.sharding.device_set) == 8
    np.testing.assert_array_equal(shd.to_host(sharded), x)


def test_multistep_matches_sequential(cpu_devices):
    """K scan-fused steps must produce the same state as K single
    steps (same data, same order)."""
    import numpy as np
    import optax

    from edl_tpu.models import ctr
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.train.trainer import (
        TrainState,
        global_batch,
        make_train_multistep,
        make_train_step,
        shard_state,
        stack_batches,
    )

    plan = MeshPlan.data_parallel(8)
    mesh = plan.build()
    tx = optax.adam(1e-2)
    rng = np.random.RandomState(0)
    raw = [ctr.synthetic_batch(rng, 64, vocab=512) for _ in range(3)]

    def fresh():
        return shard_state(
            TrainState.create(
                ctr.init_params(jax.random.PRNGKey(0), vocab=512, emb=8), tx
            ),
            plan,
            mesh,
        )

    step = make_train_step(ctr.loss_fn, tx, plan, mesh)
    s1 = fresh()
    losses_seq = []
    for b in raw:
        s1, m = step(s1, global_batch(b, plan, mesh))
        losses_seq.append(float(m["loss"]))

    multi = make_train_multistep(ctr.loss_fn, tx, plan, mesh)
    s2, m2 = multi(fresh(), stack_batches(raw, plan, mesh))
    np.testing.assert_allclose(
        np.asarray(m2["losses"]), np.asarray(losses_seq), rtol=2e-5
    )
    assert int(s2.step) == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params),
        jax.tree_util.tree_leaves(s2.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        )


# -- multi-slice topology (VERDICT r2 Missing #5) ---------------------------


def test_multislice_outer_axes_cross_slices(cpu_devices):
    """On a 2-slice fleet, dp lands ACROSS slices (DCN) while fsdp/tp
    stay inside one slice's ICI — the scaling-book hybrid layout."""
    slices = [0, 0, 0, 0, 1, 1, 1, 1]
    plan = MeshPlan.create(dp=2, fsdp=2, tp=2)
    mesh = plan.build(cpu_devices, slices=slices)
    by_id = {id(d): s for d, s in zip(cpu_devices, slices)}
    devs = np.asarray(mesh.devices)
    # dp coordinate 0 is entirely slice 0; dp coordinate 1 slice 1
    assert {by_id[id(d)] for d in devs[0].flat} == {0}
    assert {by_id[id(d)] for d in devs[1].flat} == {1}


def test_multislice_pp_crosses_slices(cpu_devices):
    slices = [0, 0, 0, 0, 1, 1, 1, 1]
    plan = MeshPlan.create(pp=2, tp=4)
    mesh = plan.build(cpu_devices, slices=slices)
    by_id = {id(d): s for d, s in zip(cpu_devices, slices)}
    devs = np.asarray(mesh.devices)
    assert {by_id[id(d)] for d in devs[0].flat} == {0}
    assert {by_id[id(d)] for d in devs[1].flat} == {1}


def test_multislice_inner_straddle_rejected(cpu_devices):
    """A per-layer collective over DCN is a config error, not a
    degraded mode: fsdp spanning both slices must fail loudly."""
    slices = [0, 0, 0, 0, 1, 1, 1, 1]
    plan = MeshPlan.create(fsdp=8)
    with pytest.raises(ValueError, match="straddle a slice"):
        plan.build(cpu_devices, slices=slices)


def test_multislice_dp_absorbs_uneven_outer(cpu_devices):
    # dp=4 over 2 slices: two dp coordinates per slice — legal
    slices = [0, 0, 0, 0, 1, 1, 1, 1]
    plan = MeshPlan.create(dp=4, tp=2)
    mesh = plan.build(cpu_devices, slices=slices)
    by_id = {id(d): s for d, s in zip(cpu_devices, slices)}
    devs = np.asarray(mesh.devices)
    for i, want in enumerate([0, 0, 1, 1]):
        assert {by_id[id(d)] for d in devs[i].flat} == {want}


def test_single_slice_order_unchanged(cpu_devices):
    # without slice info the device order is exactly as passed
    plan = MeshPlan.create(dp=8)
    mesh = plan.build(cpu_devices)
    assert list(np.asarray(mesh.devices).flat) == list(cpu_devices)
