"""MoE transformer model family — expert parallelism as a full model
(reference has none; SURVEY §2.5 "Expert parallelism: NO")."""

import numpy as np
import optax

import jax
import jax.numpy as jnp

from edl_tpu.models import moe
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.parallel import sharding as shd
from edl_tpu.train.trainer import (
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def _run(plan, steps=20, seed=0):
    cfg = moe.MoEConfig.tiny()
    mesh = plan.build()
    params = moe.init_params(jax.random.PRNGKey(1), cfg)
    tx = optax.adam(3e-3)
    pspecs = moe.param_pspecs(cfg, plan)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    step = make_train_step(moe.make_loss_fn(cfg), tx, plan, mesh, pspecs)
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        b = moe.synthetic_tokens(rng, 16, 32, cfg.vocab)
        state, m = step(state, global_batch(b, plan, mesh))
        losses.append(float(m["loss"]))
    return losses, state


def test_moe_learns(cpu_devices):
    losses, _ = _run(MeshPlan.data_parallel(4), steps=30)
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_moe_ep_parity_with_dp(cpu_devices):
    """Expert-parallel sharding is a layout choice, not a math change:
    ep=2 must reproduce the dp-only loss curve."""
    dp_losses, _ = _run(MeshPlan.data_parallel(4))
    ep_losses, state = _run(MeshPlan.create(dp=2, ep=2))
    np.testing.assert_allclose(dp_losses, ep_losses, rtol=2e-4, atol=2e-5)
    # experts actually sharded: each device holds E/2 experts of w_in
    w_in = state.params["layers"]["w_in"]
    shapes = {s.data.shape for s in w_in.addressable_shards}
    cfg = moe.MoEConfig.tiny()
    assert shapes == {
        (cfg.n_layers, cfg.n_experts // 2, cfg.d_model, cfg.d_ff)
    }


def test_moe_elastic_reshard(cpu_devices):
    """MoE through the elastic trainer: ep pinned at 2, dp grows."""
    import optax as _o

    from edl_tpu.api.job import MeshSpec
    from edl_tpu.runtime.elastic import ElasticTrainer

    cfg = moe.MoEConfig.tiny()
    tr = ElasticTrainer(
        moe.make_loss_fn(cfg),
        _o.adam(3e-3),
        mesh_spec=MeshSpec(ep=2),
        per_chip_batch=8,
        param_pspecs=lambda plan: moe.param_pspecs(cfg, plan),
    )
    tr.start(moe.init_params(jax.random.PRNGKey(0), cfg), 2)
    rng = np.random.RandomState(1)
    data = lambda bs: moe.synthetic_tokens(rng, bs, 32, cfg.vocab)
    tr.train_steps(data, 4)
    tr.request_rescale(4)  # 4 workers x 1 chip: dp 1->2, ep stays 2
    rep = tr.train_steps(data, 8)
    assert [(e.from_workers, e.to_workers) for e in rep.reshards] == [(2, 4)]
    assert tr.plan.axis_size("ep") == 2
    assert np.mean(rep.losses[-4:]) < rep.losses[0]
