"""Dynamic int8 MXU matmul (ops/int8_matmul.py) — the 2x training
throughput lever. Oracle: the exact dense matmul; the quantizer's
error budget is slicemax/254 per operand element, so products of
gaussian operands must land within ~1% relative Frobenius error, and
STE gradients must track the exact gradients to the same order."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models import llama
from edl_tpu.ops.int8_matmul import int8_matmul
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import (
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)


def _rel_fro(got, want):
    return float(
        np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-12)
    )


def test_forward_close_to_exact():
    k = jax.random.PRNGKey(0)
    a = jax.random.normal(k, (64, 96), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (96, 48), jnp.float32)
    got = np.asarray(int8_matmul(a, w))
    want = np.asarray(a @ w)
    assert _rel_fro(got, want) < 0.015


def test_forward_3d_and_dtype():
    a = jax.random.normal(jax.random.PRNGKey(2), (4, 7, 32), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(3), (32, 24), jnp.float32)
    y = int8_matmul(a, w)
    assert y.shape == (4, 7, 24)
    assert y.dtype == jnp.bfloat16


def test_zero_slices_no_nan():
    # all-zero rows/cols exercise the scale-1 guard (no 0/0)
    a = jnp.zeros((8, 16), jnp.float32)
    w = jnp.zeros((16, 8), jnp.float32)
    y = int8_matmul(a, w)
    np.testing.assert_array_equal(np.asarray(y), 0.0)
    da, dw = jax.grad(lambda a, w: int8_matmul(a, w).sum(), (0, 1))(a, w)
    assert np.isfinite(np.asarray(da)).all()
    assert np.isfinite(np.asarray(dw)).all()


def test_gradients_track_exact():
    """STE dgrad/wgrad (each an int8 dot with fresh contraction-axis
    scales) must match the exact matmul's gradients to quantization
    noise."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(4), 3)
    a = jax.random.normal(k1, (32, 48), jnp.float32)
    w = jax.random.normal(k2, (48, 40), jnp.float32)
    ct = jax.random.normal(k3, (32, 40), jnp.float32)

    def loss_q(a, w):
        return (int8_matmul(a, w) * ct).sum()

    def loss_d(a, w):
        return ((a @ w) * ct).sum()

    da_q, dw_q = jax.grad(loss_q, (0, 1))(a, w)
    da_d, dw_d = jax.grad(loss_d, (0, 1))(a, w)
    assert _rel_fro(np.asarray(da_q), np.asarray(da_d)) < 0.02
    assert _rel_fro(np.asarray(dw_q), np.asarray(dw_d)) < 0.02


def test_wgrad_bf16_knob():
    """Satellite (ADVICE r6): ``wgrad_bf16=True`` keeps the weight
    gradient on the bf16 path — dw matches the EXACT wgrad to bf16
    rounding (far inside the int8 path's quantization band) while
    dgrad and the forward stay on the int8 path (unchanged vs the
    default)."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    a = jax.random.normal(k1, (64, 48), jnp.float32)
    w = jax.random.normal(k2, (48, 40), jnp.float32)
    ct = jax.random.normal(k3, (64, 40), jnp.float32)
    # an outlier in the gradient: the exact failure mode the knob
    # mitigates — one huge element crushes the whole M-slice's absmax
    # resolution for the int8 wgrad, but not for the bf16 one
    ct = ct.at[0, 0].set(500.0)

    def loss(a, w, wb):
        return (int8_matmul(a, w, wgrad_bf16=wb) * ct).sum()

    da_b, dw_b = jax.grad(loss, (0, 1))(a, w, True)
    da_q, dw_q = jax.grad(loss, (0, 1))(a, w, False)
    da_d, dw_d = jax.grad(
        lambda a, w: ((a @ w) * ct).sum(), (0, 1)
    )(a, w)
    # forward identical either way (same int8 path)
    np.testing.assert_array_equal(
        np.asarray(int8_matmul(a, w, wgrad_bf16=True)),
        np.asarray(int8_matmul(a, w)),
    )
    # dgrad identical either way (still int8)
    np.testing.assert_array_equal(np.asarray(da_b), np.asarray(da_q))
    # bf16 wgrad is ~bf16-rounding-exact; int8 wgrad is visibly worse
    # under the outlier
    err_b = _rel_fro(np.asarray(dw_b), np.asarray(dw_d))
    err_q = _rel_fro(np.asarray(dw_q), np.asarray(dw_d))
    assert err_b < 0.005, err_b
    assert err_b < err_q / 5, (err_b, err_q)


def test_wgrad_bf16_plumbs_through_llama_config():
    """LlamaConfig.int8_wgrad_bf16 reaches every projection matmul's
    backward: gradients differ from the all-int8 run (the knob is
    live) and stay finite; the forward is identical (fwd stays
    int8)."""
    import dataclasses

    batch = jax.tree_util.tree_map(
        jnp.asarray,
        llama.synthetic_tokens(np.random.RandomState(0), 2, 16, 256),
    )
    base = dataclasses.replace(llama.LlamaConfig.tiny(), int8_mxu=True)
    params = llama.init_params(jax.random.PRNGKey(0), base)
    out = {}
    for wb in (False, True):
        cfg = dataclasses.replace(base, int8_wgrad_bf16=wb)
        l, g = jax.value_and_grad(llama.make_loss_fn(cfg))(params, batch)
        out[wb] = (float(l), g)
    assert out[False][0] == out[True][0]  # forward path unchanged
    gq = np.asarray(out[False][1]["layers"]["wq"])
    gb = np.asarray(out[True][1]["layers"]["wq"])
    assert np.isfinite(gb).all()
    assert not np.array_equal(gq, gb)  # wgrad actually rerouted
    assert _rel_fro(gb, gq) < 0.1  # ...but only by quantization noise


def test_llama_int8_mxu_trains():
    """cfg.int8_mxu routes the seven projection matmuls through the
    quantized path; a tiny model must still train (loss falls) and its
    curve must track the full-precision run closely."""
    batches = [
        llama.synthetic_tokens(np.random.RandomState(i), 8, 16, 256)
        for i in range(20)
    ]

    def run(int8):
        cfg = llama.LlamaConfig.tiny()
        if int8:
            import dataclasses

            cfg = dataclasses.replace(cfg, int8_mxu=True)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        loss_fn = llama.make_loss_fn(cfg)
        step = jax.jit(
            lambda p, o, b: _step(p, o, b, loss_fn, tx)
        )
        losses = []
        for b in batches:
            (params, opt), l = step(
                params, opt, jax.tree_util.tree_map(jnp.asarray, b)
            )
            losses.append(float(l))
        return losses

    def _step(p, o, b, loss_fn, tx):
        l, g = jax.value_and_grad(loss_fn)(p, b)
        updates, o = tx.update(g, o, p)
        return (optax.apply_updates(p, updates), o), l

    l_f32 = run(False)
    l_int8 = run(True)
    assert l_int8[-1] < l_int8[0] - 0.5, l_int8
    # same data, same seed: curves differ only by quantization noise
    assert abs(l_int8[-1] - l_f32[-1]) < 0.15 * abs(l_f32[0] - l_f32[-1]), (
        l_f32[-1],
        l_int8[-1],
    )


def test_int8_mxu_composes_with_remat():
    import dataclasses

    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(), int8_mxu=True, remat=True
    )
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    batch = jax.tree_util.tree_map(
        jnp.asarray, llama.synthetic_tokens(np.random.RandomState(0), 2, 16, cfg.vocab)
    )
    loss_fn = llama.make_loss_fn(cfg)
    l, g = jax.value_and_grad(loss_fn)(params, batch)
    assert np.isfinite(float(l))
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(x)).all() for x in flat)


def test_int8_mxu_sharded_training(cpu_devices):
    """The dynamic absmax reductions and int8 dots must compile and
    train under a tp x fsdp GSPMD sharding (the dryrun/production
    layout)."""
    import dataclasses

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), int8_mxu=True)
    plan = MeshPlan.create(dp=2, fsdp=2, tp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adam(3e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    step = make_train_step(
        llama.make_loss_fn(cfg), tx, plan, mesh, param_pspecs=pspecs
    )
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(20):
        b = llama.synthetic_tokens(rng, 16, 32, cfg.vocab)
        state, m = step(state, global_batch(b, plan, mesh))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_edl_int8_mxu_env_routes_into_llama_workload():
    """EDL_INT8_MXU=1 must reach the llama workload's model config: the
    quantized loss differs from the dense loss by exactly quantization
    noise (nonzero but small), and the export record stays dense."""
    from edl_tpu.runtime.worker_config import WorkerConfig
    from edl_tpu.runtime.workloads import WORKLOADS

    base_env = {
        "EDL_JOB_NAME": "t", "EDL_COORDINATOR": "127.0.0.1:1",
        "EDL_MODEL": "llama", "EDL_VOCAB": "256",
    }
    cfg_d = WorkerConfig.from_env(base_env)
    cfg_q = WorkerConfig.from_env({**base_env, "EDL_INT8_MXU": "1"})
    assert not cfg_d.int8_mxu and cfg_q.int8_mxu

    wl_d = WORKLOADS["llama"](cfg_d)
    wl_q = WORKLOADS["llama"](cfg_q)
    # training-only flag: the architecture record (what exports carry)
    # must not change
    assert wl_d.model_meta == wl_q.model_meta

    params = wl_d.init_params()
    batch = jax.tree_util.tree_map(
        jnp.asarray,
        llama.synthetic_tokens(np.random.RandomState(0), 4, 16, 256),
    )
    l_d = float(wl_d.loss_fn(params, batch))
    l_q = float(wl_q.loss_fn(params, batch))
    assert l_d != l_q  # the quantized path really ran
    assert abs(l_d - l_q) < 0.05 * l_d


def test_generate_strips_int8_mxu():
    """The training-only flag must not leak into serving: generate
    with an int8_mxu config produces bit-identical tokens to the plain
    config (the flag is stripped before the decode program builds)."""
    import dataclasses

    cfg = llama.LlamaConfig.tiny()
    cfg_q = dataclasses.replace(cfg, int8_mxu=True)
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab, (2, 8), np.int32)
    )
    got = np.asarray(llama.generate(params, prompt, cfg_q, max_new=6))
    want = np.asarray(llama.generate(params, prompt, cfg, max_new=6))
    np.testing.assert_array_equal(got, want)


def test_int8_mxu_pp_matches_dp(cpu_devices):
    """int8 under pipeline parallelism: a pp=2 int8 run must match a
    dp-only int8 run — the mesh layout must not change the quantized
    math. Tolerance is looser than the bf16 parity tests: a reduction-
    order difference that lands an operand exactly on a round()
    boundary shifts that value by its quantization step (absmax/127),
    which the exact-f32 tests never see."""
    import dataclasses

    from tests.llama_harness import loss_curve

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), int8_mxu=True)
    l_dp = loss_curve(MeshPlan.data_parallel(8), cfg=cfg)
    l_pp = loss_curve(MeshPlan.create(dp=4, pp=2), cfg=cfg)
    np.testing.assert_allclose(l_pp, l_dp, rtol=5e-3, atol=5e-4)


def test_int8_mxu_sp_matches_dp(cpu_devices):
    """int8 under sequence parallelism (ring attention inside
    shard_map): same layout-invariance contract as the pp test, same
    round()-boundary tolerance rationale."""
    import dataclasses

    from tests.llama_harness import loss_curve

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), int8_mxu=True)
    l_dp = loss_curve(MeshPlan.data_parallel(8), cfg=cfg)
    l_sp = loss_curve(MeshPlan.create(dp=4, sp=2), cfg=cfg)
    np.testing.assert_allclose(l_sp, l_dp, rtol=5e-3, atol=5e-4)


# -- batched (MoE expert) int8 matmul ---------------------------------------


def test_batched_forward_close_to_exact():
    from edl_tpu.ops.int8_matmul import int8_batched_matmul

    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    a = jax.random.normal(k1, (4, 24, 64), jnp.float32)
    w = jax.random.normal(k2, (4, 64, 32), jnp.float32)
    got = np.asarray(int8_batched_matmul(a, w))
    want = np.asarray(jnp.einsum("eck,ekn->ecn", a, w))
    assert _rel_fro(got, want) < 0.015


def test_batched_gradients_track_exact():
    from edl_tpu.ops.int8_matmul import int8_batched_matmul

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    a = jax.random.normal(k1, (3, 16, 40), jnp.float32)
    w = jax.random.normal(k2, (3, 40, 24), jnp.float32)
    ct = jax.random.normal(k3, (3, 16, 24), jnp.float32)

    da_q, dw_q = jax.grad(
        lambda a, w: (int8_batched_matmul(a, w) * ct).sum(), (0, 1)
    )(a, w)
    da_d, dw_d = jax.grad(
        lambda a, w: (jnp.einsum("eck,ekn->ecn", a, w) * ct).sum(), (0, 1)
    )(a, w)
    assert _rel_fro(np.asarray(da_q), np.asarray(da_d)) < 0.02
    assert _rel_fro(np.asarray(dw_q), np.asarray(dw_d)) < 0.02


def test_moe_int8_mxu_trains_and_meta_stays_dense():
    """MoEConfig.int8_mxu routes attention projections + expert
    batched matmuls; the tiny model trains with a curve close to the
    dense run, and the export architecture record never carries the
    training-only flag."""
    import dataclasses

    from edl_tpu.models import moe

    cfg_d = moe.MoEConfig.tiny()
    cfg_q = dataclasses.replace(cfg_d, int8_mxu=True)
    assert cfg_d.to_meta() == cfg_q.to_meta()
    assert "int8_mxu" not in cfg_q.to_meta()
    # from_meta roundtrip leaves the flag at its (dense) default
    assert not moe.MoEConfig.from_meta(cfg_q.to_meta()).int8_mxu

    batches = [
        moe.synthetic_tokens(np.random.RandomState(i), 8, 16, 256)
        for i in range(15)
    ]

    def run(cfg):
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        loss_fn = moe.make_loss_fn(cfg)

        @jax.jit
        def step(p, o, b):
            l, g = jax.value_and_grad(loss_fn)(p, b)
            updates, o = tx.update(g, o, p)
            return (optax.apply_updates(p, updates), o), l

        losses = []
        for b in batches:
            (params, opt), l = step(
                params, opt, jax.tree_util.tree_map(jnp.asarray, b)
            )
            losses.append(float(l))
        return losses

    l_d = run(cfg_d)
    l_q = run(cfg_q)
    assert l_q[-1] < l_q[0] - 0.5, l_q
    assert abs(l_q[-1] - l_d[-1]) < 0.15 * abs(l_d[0] - l_d[-1])


def test_edl_int8_mxu_env_routes_into_moe_workload():
    from edl_tpu.runtime.worker_config import WorkerConfig
    from edl_tpu.runtime.workloads import WORKLOADS

    base_env = {
        "EDL_JOB_NAME": "t", "EDL_COORDINATOR": "127.0.0.1:1",
        "EDL_MODEL": "moe", "EDL_VOCAB": "256",
    }
    wl_d = WORKLOADS["moe"](WorkerConfig.from_env(base_env))
    wl_q = WORKLOADS["moe"](
        WorkerConfig.from_env({**base_env, "EDL_INT8_MXU": "1"})
    )
    assert wl_d.model_meta == wl_q.model_meta

    from edl_tpu.models import moe

    params = wl_d.init_params()
    batch = jax.tree_util.tree_map(
        jnp.asarray,
        moe.synthetic_tokens(np.random.RandomState(0), 4, 16, 256),
    )
    l_d = float(wl_d.loss_fn(params, batch))
    l_q = float(wl_q.loss_fn(params, batch))
    assert l_d != l_q  # the quantized path really ran
    assert abs(l_d - l_q) < 0.05 * l_d
