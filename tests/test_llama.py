"""Llama decoder: correctness, TP×FSDP sharded training, elastic reshard."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from edl_tpu.api.job import MeshSpec
from edl_tpu.models import llama
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.train.trainer import TrainState, global_batch, make_train_step, shard_state


def test_forward_shapes_and_causality():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab
    logits = llama.forward(params, jnp.asarray(toks), cfg)
    assert logits.shape == (2, 16, cfg.vocab)
    # causality: changing a future token must not affect earlier logits
    toks2 = toks.copy()
    toks2[:, 10:] = (toks2[:, 10:] + 7) % cfg.vocab
    logits2 = llama.forward(params, jnp.asarray(toks2), cfg)
    np.testing.assert_allclose(logits[:, :10], logits2[:, :10], atol=1e-5)
    assert not np.allclose(logits[:, 10:], logits2[:, 10:])


def test_tp_fsdp_training(cpu_devices):
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=2, fsdp=2, tp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adam(3e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    # tp really shards the head dim; fsdp really shards d_model
    wq = state.params["layers"]["wq"]
    wq_shard = (cfg.n_layers, cfg.d_model // 2, cfg.n_heads * cfg.head_dim // 2)
    assert {s.data.shape for s in wq.addressable_shards} == {wq_shard}
    # Adam moments must mirror the TP sharding of their params
    mu_wq = state.opt_state[0].mu["layers"]["wq"]
    assert {s.data.shape for s in mu_wq.addressable_shards} == {wq_shard}
    loss_fn = llama.make_loss_fn(cfg)
    step = make_train_step(loss_fn, tx, plan, mesh, param_pspecs=pspecs)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(15):
        b = llama.synthetic_tokens(rng, 16, 32, cfg.vocab)
        state, m = step(state, global_batch(b, plan, mesh))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_tp_matches_unsharded(cpu_devices):
    # The sharding must be a layout choice: tp=2/fsdp=2 loss == dp loss.
    cfg = llama.LlamaConfig.tiny()
    rng_batches = [
        llama.synthetic_tokens(np.random.RandomState(i), 8, 16, cfg.vocab)
        for i in range(3)
    ]

    def run(plan, pspecs):
        mesh = plan.build()
        params = llama.init_params(jax.random.PRNGKey(1), cfg)
        tx = optax.sgd(1e-2)
        state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
        step = make_train_step(llama.make_loss_fn(cfg), tx, plan, mesh, pspecs)
        out = []
        for b in rng_batches:
            state, m = step(state, global_batch(b, plan, mesh))
            out.append(float(m["loss"]))
        return out

    plan_tp = MeshPlan.create(dp=2, fsdp=2, tp=2)
    l_tp = run(plan_tp, llama.param_pspecs(cfg, plan_tp))
    plan_dp = MeshPlan.data_parallel(8)
    l_dp = run(plan_dp, None)
    np.testing.assert_allclose(l_tp, l_dp, rtol=1e-4, atol=1e-5)


from tests.llama_harness import loss_curve as _loss_curve  # noqa: E402
# (shared with test_int8_matmul.py via the non-test-module pattern —
# importing one test module from another double-imports it under
# pytest's prepend import mode)


def test_sp_ring_matches_dp(cpu_devices):
    """sp=2 (ring attention) — the long-context strategy as a TRAINABLE
    mesh axis: full train steps, loss == dp-only loss (SURVEY §2.5 SP,
    VERDICT r2 #1a)."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_sp = _loss_curve(MeshPlan.create(dp=4, sp=2))
    np.testing.assert_allclose(l_sp, l_dp, rtol=1e-4, atol=1e-5)


def test_sp_ulysses_matches_dp(cpu_devices):
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_ul = _loss_curve(MeshPlan.create(dp=4, sp=2), sp_impl="ulysses")
    np.testing.assert_allclose(l_ul, l_dp, rtol=1e-4, atol=1e-5)


def test_sp_with_fsdp_matches_dp(cpu_devices):
    """sp composes with fsdp+remat (the long-context production mesh)."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_mix = _loss_curve(MeshPlan.create(fsdp=2, sp=2, dp=2), remat=True)
    np.testing.assert_allclose(l_mix, l_dp, rtol=1e-4, atol=1e-5)


def test_pp_matches_dp(cpu_devices):
    """pp=2 (GPipe over ppermute) as a TRAINABLE mesh axis (VERDICT r2
    #1b): full train steps through pipeline_apply, loss == dp loss."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_pp = _loss_curve(MeshPlan.create(dp=4, pp=2))
    np.testing.assert_allclose(l_pp, l_dp, rtol=1e-4, atol=1e-5)
    # more microbatches than stages (the realistic bubble regime)
    l_pp4 = _loss_curve(MeshPlan.create(dp=2, pp=2), pp_microbatches=4)
    np.testing.assert_allclose(l_pp4, l_dp, rtol=1e-4, atol=1e-5)


def test_pp_fsdp_matches_dp(cpu_devices):
    """pp composes with fsdp (3D dp×pp×fsdp — VERDICT r3 weak #2): the
    pipeline shard_map gathers each stage's fsdp-sharded weights
    per step (ZeRO-style) while the microbatch rows stay split over
    fsdp, and the loss equals dp."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_mix = _loss_curve(MeshPlan.create(dp=2, pp=2, fsdp=2))
    np.testing.assert_allclose(l_mix, l_dp, rtol=1e-4, atol=1e-5)


def test_pp_tp_matches_dp(cpu_devices):
    """pp×tp: tp acts as memory sharding inside a pipeline stage (the
    stage gathers tp-sharded weights per step; stage compute is
    replicated over tp) — a layout choice, same loss as dp."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_mix = _loss_curve(MeshPlan.create(dp=2, pp=2, tp=2))
    np.testing.assert_allclose(l_mix, l_dp, rtol=1e-4, atol=1e-5)


def test_pp_fsdp_tp_matches_dp(cpu_devices):
    """The flagship 3D mesh pp×fsdp×tp trains: full train steps, loss
    == dp loss, with more microbatches than stages."""
    l_dp = _loss_curve(MeshPlan.data_parallel(8))
    l_3d = _loss_curve(MeshPlan.create(pp=2, fsdp=2, tp=2))
    np.testing.assert_allclose(l_3d, l_dp, rtol=1e-4, atol=1e-5)
    l_3d4 = _loss_curve(
        MeshPlan.create(pp=2, fsdp=2, tp=2), pp_microbatches=4
    )
    np.testing.assert_allclose(l_3d4, l_dp, rtol=1e-4, atol=1e-5)


def test_pp_fsdp_tp_shards_moments_per_stage(cpu_devices):
    """On the 3D mesh every big weight (and its Adam moments) is REALLY
    sharded along all three axes: layer dim over pp, d_model over fsdp,
    head dim over tp — at rest each device holds 1/8 of wq."""
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(pp=2, fsdp=2, tp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adam(1e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    shard = (
        cfg.n_layers // 2,
        cfg.d_model // 2,
        cfg.n_heads * cfg.head_dim // 2,
    )
    wq = state.params["layers"]["wq"]
    assert {s.data.shape for s in wq.addressable_shards} == {shard}
    mu_wq = state.opt_state[0].mu["layers"]["wq"]
    assert {s.data.shape for s in mu_wq.addressable_shards} == {shard}


def test_pp_shards_layer_axis_and_moments(cpu_devices):
    """With a pp axis the scan-stacked layer dim is REALLY split across
    stages (each device holds only its stage's layers), and Adam
    moments follow."""
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=4, pp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adam(1e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    wq = state.params["layers"]["wq"]
    per_stage = (
        cfg.n_layers // 2,
        cfg.d_model,
        cfg.n_heads * cfg.head_dim,
    )
    assert {s.data.shape for s in wq.addressable_shards} == {per_stage}
    mu_wq = state.opt_state[0].mu["layers"]["wq"]
    assert {s.data.shape for s in mu_wq.addressable_shards} == {per_stage}


def test_sp_sequence_shards_activations(cpu_devices):
    """The sp program really sequence-shards the compute: logits come
    out split over sp on the T dim (no device saw the full sequence)."""
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=2, sp=4)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = np.arange(4 * 16, dtype=np.int32).reshape(4, 16) % cfg.vocab

    fwd = jax.jit(
        lambda p, t: llama.forward(p, t, cfg, mesh=mesh, plan=plan)
    )
    logits = fwd(params, jnp.asarray(toks))
    spec = logits.sharding.spec
    assert spec[1] == "sp", spec
    # and the math still matches the unsharded oracle
    ref = llama.forward(params, jnp.asarray(toks), cfg)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=2e-4)


def test_sp_pp_combination_rejected(cpu_devices):
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=2, sp=2, pp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((4, 16), jnp.int32)
    import pytest

    with pytest.raises(ValueError, match="sp and pp"):
        llama.forward(params, toks, cfg, mesh=mesh, plan=plan)


def _remat_loss_and_grads(cfg, t=16):
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = llama.synthetic_tokens(np.random.RandomState(0), 4, t, cfg.vocab)
    loss_fn = llama.make_loss_fn(cfg)
    loss, grads = jax.value_and_grad(loss_fn)(params, toks)
    return float(loss), grads


def test_remat_policies_grad_and_match():
    """Every remat policy produces the same loss and finite grads as
    the no-remat baseline (ADVICE r2: the policy dial had no coverage)."""
    import dataclasses

    base = llama.LlamaConfig.tiny()
    l0, g0 = _remat_loss_and_grads(base)
    for policy in ("full", "mlp", "dots"):
        cfg = dataclasses.replace(base, remat=True, remat_policy=policy)
        l, g = _remat_loss_and_grads(cfg)
        np.testing.assert_allclose(l, l0, rtol=1e-6, err_msg=policy)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g,
            g0,
        )


def test_remat_attn_policy_runs_with_flash():
    """remat_policy="attn" with the flash kernel: traces, grads finite,
    loss matches the baseline (interpret-mode pallas on CPU)."""
    import dataclasses

    base = llama.LlamaConfig.tiny()
    # flash kernel block sizes need T >= the fitted block: use T=128
    cfg = dataclasses.replace(
        base, remat=True, remat_policy="attn", use_flash=True
    )
    from edl_tpu.ops.flash_attention import flash_supported

    t = 128
    assert flash_supported(t)
    l_attn, g = _remat_loss_and_grads(cfg, t=t)
    ref = dataclasses.replace(base, use_flash=True)
    l_ref, _ = _remat_loss_and_grads(ref, t=t)
    np.testing.assert_allclose(l_attn, l_ref, rtol=1e-4)
    assert all(
        np.isfinite(np.asarray(x)).all()
        for x in jax.tree_util.tree_leaves(g)
    )


def test_remat_attn_policy_guards():
    """The attn policy refuses configurations where the flash residual
    names would not exist (silent degradation to full remat)."""
    import dataclasses

    import pytest

    base = llama.LlamaConfig.tiny()
    # no flash at all -> _remat_policy raises
    cfg = dataclasses.replace(base, remat=True, remat_policy="attn")
    with pytest.raises(ValueError, match="use_flash"):
        _remat_loss_and_grads(cfg)
    # flash on, but an unsupported sequence length -> forward raises
    # instead of silently taking the dense path (ADVICE r2)
    cfg = dataclasses.replace(
        base, remat=True, remat_policy="attn", use_flash=True
    )
    from edl_tpu.ops.flash_attention import flash_supported

    t_bad = 520  # > 512 and not a multiple of the 128-lane tile
    assert not flash_supported(t_bad)
    with pytest.raises(ValueError, match="not flash-supported"):
        _remat_loss_and_grads(cfg, t=t_bad)
    # sp mesh: ring/ulysses never run the flash kernel -> rejected
    plan = MeshPlan.create(dp=4, sp=2)
    mesh = plan.build()
    cfg = dataclasses.replace(
        base, remat=True, remat_policy="attn", use_flash=True
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="flash kernel"):
        llama.forward(
            params, jnp.zeros((4, 128), jnp.int32), cfg, mesh=mesh, plan=plan
        )


def test_llama_elastic_sp_reshard(cpu_devices):
    """sp pinned in the in-process elastic runtime: the mesh-aware loss
    factory rebuilds the ring-attention program at every reshard while
    dp absorbs the worker change."""
    cfg = llama.LlamaConfig.tiny()
    tr = ElasticTrainer(
        None,
        optax.adam(1e-3),
        mesh_spec=MeshSpec(sp=2),
        chips_per_worker=2,
        per_chip_batch=4,
        param_pspecs=lambda plan: llama.param_pspecs(cfg, plan),
        make_loss=lambda plan, mesh: llama.make_loss_fn(cfg, plan, mesh),
    )
    tr.start(llama.init_params(jax.random.PRNGKey(0), cfg), n_workers=2)
    rng = np.random.RandomState(0)

    def data(bs):
        return llama.synthetic_tokens(rng, bs, 16, cfg.vocab)

    tr.train_steps(data, 3)
    tr.request_rescale(4)
    tr.train_steps(data, 3)
    assert tr.plan.describe() == {"dp": 4, "sp": 2}
    assert len(tr.report.reshards) == 1
    assert int(tr.state.step) == 6


def test_llama_elastic_fsdp_reshard(cpu_devices):
    # The BASELINE headline config in miniature: elastic FSDP llama.
    cfg = llama.LlamaConfig.tiny()
    plan_spec = MeshSpec(fsdp=2)
    tr = ElasticTrainer(
        llama.make_loss_fn(cfg),
        optax.adam(1e-3),
        mesh_spec=plan_spec,
        chips_per_worker=2,
        per_chip_batch=4,
        # plan-aware: re-evaluated at every reshard
        param_pspecs=lambda plan: llama.param_pspecs(cfg, plan),
    )
    tr.start(llama.init_params(jax.random.PRNGKey(0), cfg), n_workers=2)
    rng = np.random.RandomState(0)

    def data(bs):
        return llama.synthetic_tokens(rng, bs, 16, cfg.vocab)

    tr.train_steps(data, 3)
    tr.request_rescale(4)
    tr.train_steps(data, 3)
    assert tr.n_workers == 4
    assert tr.plan.describe() == {"dp": 4, "fsdp": 2}
    assert len(tr.report.reshards) == 1
    assert int(tr.state.step) == 6
