"""Llama decoder: correctness, TP×FSDP sharded training, elastic reshard."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from edl_tpu.api.job import MeshSpec
from edl_tpu.models import llama
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.train.trainer import TrainState, global_batch, make_train_step, shard_state


def test_forward_shapes_and_causality():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab
    logits = llama.forward(params, jnp.asarray(toks), cfg)
    assert logits.shape == (2, 16, cfg.vocab)
    # causality: changing a future token must not affect earlier logits
    toks2 = toks.copy()
    toks2[:, 10:] = (toks2[:, 10:] + 7) % cfg.vocab
    logits2 = llama.forward(params, jnp.asarray(toks2), cfg)
    np.testing.assert_allclose(logits[:, :10], logits2[:, :10], atol=1e-5)
    assert not np.allclose(logits[:, 10:], logits2[:, 10:])


def test_tp_fsdp_training(cpu_devices):
    cfg = llama.LlamaConfig.tiny()
    plan = MeshPlan.create(dp=2, fsdp=2, tp=2)
    mesh = plan.build()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adam(3e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    # tp really shards the head dim; fsdp really shards d_model
    wq = state.params["layers"]["wq"]
    wq_shard = (cfg.n_layers, cfg.d_model // 2, cfg.n_heads * cfg.head_dim // 2)
    assert {s.data.shape for s in wq.addressable_shards} == {wq_shard}
    # Adam moments must mirror the TP sharding of their params
    mu_wq = state.opt_state[0].mu["layers"]["wq"]
    assert {s.data.shape for s in mu_wq.addressable_shards} == {wq_shard}
    loss_fn = llama.make_loss_fn(cfg)
    step = make_train_step(loss_fn, tx, plan, mesh, param_pspecs=pspecs)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(15):
        b = llama.synthetic_tokens(rng, 16, 32, cfg.vocab)
        state, m = step(state, global_batch(b, plan, mesh))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_tp_matches_unsharded(cpu_devices):
    # The sharding must be a layout choice: tp=2/fsdp=2 loss == dp loss.
    cfg = llama.LlamaConfig.tiny()
    rng_batches = [
        llama.synthetic_tokens(np.random.RandomState(i), 8, 16, cfg.vocab)
        for i in range(3)
    ]

    def run(plan, pspecs):
        mesh = plan.build()
        params = llama.init_params(jax.random.PRNGKey(1), cfg)
        tx = optax.sgd(1e-2)
        state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
        step = make_train_step(llama.make_loss_fn(cfg), tx, plan, mesh, pspecs)
        out = []
        for b in rng_batches:
            state, m = step(state, global_batch(b, plan, mesh))
            out.append(float(m["loss"]))
        return out

    plan_tp = MeshPlan.create(dp=2, fsdp=2, tp=2)
    l_tp = run(plan_tp, llama.param_pspecs(cfg, plan_tp))
    plan_dp = MeshPlan.data_parallel(8)
    l_dp = run(plan_dp, None)
    np.testing.assert_allclose(l_tp, l_dp, rtol=1e-4, atol=1e-5)


def test_llama_elastic_fsdp_reshard(cpu_devices):
    # The BASELINE headline config in miniature: elastic FSDP llama.
    cfg = llama.LlamaConfig.tiny()
    plan_spec = MeshSpec(fsdp=2)
    tr = ElasticTrainer(
        llama.make_loss_fn(cfg),
        optax.adam(1e-3),
        mesh_spec=plan_spec,
        chips_per_worker=2,
        per_chip_batch=4,
        # plan-aware: re-evaluated at every reshard
        param_pspecs=lambda plan: llama.param_pspecs(cfg, plan),
    )
    tr.start(llama.init_params(jax.random.PRNGKey(0), cfg), n_workers=2)
    rng = np.random.RandomState(0)

    def data(bs):
        return llama.synthetic_tokens(rng, bs, 16, cfg.vocab)

    tr.train_steps(data, 3)
    tr.request_rescale(4)
    tr.train_steps(data, 3)
    assert tr.n_workers == 4
    assert tr.plan.describe() == {"dp": 4, "fsdp": 2}
    assert len(tr.report.reshards) == 1
    assert int(tr.state.step) == 6
