"""FakeCluster: census, placement, contention, conflicts, fault injection.

Exercises the in-memory backend the way the reference's controller uses
the real one (reference: pkg/cluster.go InquiryResource/JobPods/
UpdateTrainerJob), plus the watch/store surface of the API-server
stand-in.
"""

import pytest

from edl_tpu.api.job import Event, TrainingJob
from edl_tpu.api.parser import JobParser
from edl_tpu.cluster.base import ConflictError
from edl_tpu.cluster.fake import FakeCluster, FakeHost


def tpu_fleet(n_hosts=4, chips=4, cpu=8000, mem=16000):
    return FakeCluster(
        hosts=[FakeHost(f"host{i}", cpu, mem, chips) for i in range(n_hosts)]
    )


def make_job(name="j1", lo=2, hi=8, chips=4, cpu="500m", mem="1Gi"):
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                "worker": {
                    "min_replicas": lo,
                    "max_replicas": hi,
                    "resources": {
                        "requests": {"cpu": cpu, "memory": mem, "tpu": chips},
                        "limits": {"cpu": cpu, "memory": mem, "tpu": chips},
                    },
                },
            },
        }
    )
    JobParser().validate(job)
    return job


def test_census_totals_and_idle():
    c = tpu_fleet()
    r = c.inquiry_resource()
    assert r.chip_total == 16
    assert r.cpu_total_milli == 32000
    assert r.mem_total_mega == 64000
    assert r.hosts.chips_free["host0"] == 4


def test_create_workers_places_pods():
    c = tpu_fleet()
    job = make_job()
    plan = JobParser().parse_to_workers(job)
    g = c.create_worker_group(plan)
    assert g.parallelism == 2
    total, running, pending = c.job_pods(job)
    assert (total, running, pending) == (2, 2, 0)
    r = c.inquiry_resource()
    assert r.chip_limit == 8  # 2 workers * 4 chips
    assert r.cpu_request_milli == 1000


def test_scale_up_and_down_reconciles():
    c = tpu_fleet()
    job = make_job()
    c.create_worker_group(JobParser().parse_to_workers(job))
    g = c.get_worker_group(job)
    g.parallelism = 4
    c.update_worker_group(g)
    assert c.job_pods(job) == (4, 4, 0)
    g = c.get_worker_group(job)
    g.parallelism = 2
    c.update_worker_group(g)
    assert c.job_pods(job) == (2, 2, 0)
    assert c.inquiry_resource().chip_limit == 8


def test_pending_under_contention():
    # 4 hosts x 4 chips; 8 workers need 32 chips — half must pend.
    c = tpu_fleet()
    job = make_job(lo=8, hi=8)
    c.create_worker_group(JobParser().parse_to_workers(job))
    total, running, pending = c.job_pods(job)
    assert total == 8
    assert running == 4
    assert pending == 4
    r = c.inquiry_resource()
    # pending pods still count in requests (reference: InquiryResource
    # lists phase ∉ {Succeeded,Failed}, pkg/cluster.go:202-210)
    assert r.chip_limit == 32
    # ...but only placed pods consume host idle capacity
    assert sum(r.hosts.chips_free.values()) == 0


def test_stale_update_conflicts():
    c = tpu_fleet()
    job = make_job()
    c.create_worker_group(JobParser().parse_to_workers(job))
    g1 = c.get_worker_group(job)
    g2 = c.get_worker_group(job)
    g1.parallelism = 3
    c.update_worker_group(g1)
    g2.parallelism = 5
    with pytest.raises(ConflictError):
        c.update_worker_group(g2)


def test_watch_and_store():
    c = tpu_fleet()
    seen = []
    c.watch_jobs(lambda ev: seen.append((ev.type, ev.job.name)))
    job = make_job()
    c.submit_job(job)
    c.submit_job(job)
    c.delete_job(job.namespace, job.name)
    assert seen == [
        (Event.Type.ADD, "j1"),
        (Event.Type.UPDATE, "j1"),
        (Event.Type.DEL, "j1"),
    ]


def test_kill_pod_and_external_contention():
    c = tpu_fleet()
    job = make_job()
    c.create_worker_group(JobParser().parse_to_workers(job))
    pods = [p for p in c.pods.values() if p.role == "worker"]
    c.kill_pod(pods[0].name)
    total, running, pending = c.job_pods(job)
    assert running == 1
    g = c.get_worker_group(job)
    assert g.failed == 1
    # nginx-filler analog eats host CPU (reference: example/fit_a_line/nginx.yaml)
    c.add_external_pod("nginx-0", cpu_milli=7000, mem_mega=1000)
    r = c.inquiry_resource()
    assert r.cpu_request_milli >= 7000


def test_coordinator_lifecycle():
    c = tpu_fleet()
    job = make_job()
    plan = JobParser().parse_to_coordinator(job)
    coord = c.create_coordinator(plan)
    assert c.get_coordinator("default", coord.name).ready_replicas == 1
    c.delete_coordinator("default", coord.name)
    with pytest.raises(KeyError):
        c.get_coordinator("default", coord.name)
