"""In-memory Kubernetes API server for tests.

The analog of the reference's generated fake clientset (reference:
pkg/client/clientset/versioned/fake/clientset_generated.go:30-50 built
on client-go object trackers), which SURVEY §4 identifies as the
intended — but never used — harness for controller integration tests.
Here it is an actual HTTP server speaking enough of the k8s REST API
for edl_tpu.cluster.kube.KubeCluster: typed CRUD for Jobs /
Deployments / Services / TrainingJobs, list with label/field
selectors, resourceVersion bookkeeping with 409 conflicts, the status
subresource, and a crude pod-lifecycle reconciler so Jobs grow pods
like a real cluster.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

# (group, version, namespaced plural) -> kind
ROUTES = {
    ("batch/v1", "jobs"): "Job",
    ("apps/v1", "deployments"): "Deployment",
    ("v1", "services"): "Service",
    ("v1", "pods"): "Pod",
    ("v1", "nodes"): "Node",
    ("edl-tpu.org/v1", "trainingjobs"): "TrainingJob",
}

_PATH_RE = re.compile(
    r"^/(?:api/(?P<core_ver>v1)|apis/(?P<group>[^/]+)/(?P<ver>[^/]+))"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<sub>status))?$"
)


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        # objects[(gv, plural)][(ns, name)] = dict
        self.objects: Dict[Tuple[str, str], Dict[Tuple[str, str], dict]] = {
            key: {} for key in ROUTES
        }
        self.rv = 0
        # watch journal: every mutation appends an event with its own
        # monotone sequence number (the watch analog of etcd revisions)
        self.events: List[dict] = []
        # non-watch LIST hits per route — lets tests prove a streaming
        # watcher is NOT relisting every tick
        self.list_counts: Dict[Tuple[str, str], int] = {}

    def next_rv(self) -> str:
        self.rv += 1
        return str(self.rv)

    def compact_events(self, keep_last: int = 0) -> None:
        """Drop all but the newest ``keep_last`` journal events — the
        etcd-compaction analog. A watch resuming from an rv older than
        the journal head then gets a 410 Gone ERROR event mid-stream
        (k8s semantics), forcing the client through its relist path."""
        with self.lock:
            self.events = self.events[len(self.events) - keep_last:] if keep_last else []

    def record(self, key, ns: str, name: str, etype: str, obj: dict) -> None:
        """Append a watch event (caller holds the lock). The event's
        object carries the event's own resourceVersion — as in k8s,
        where the mutation's new rv IS what the watch delivers and what
        clients resume from."""
        rv = int(self.next_rv())
        copy = json.loads(json.dumps(obj))
        copy.setdefault("metadata", {})["resourceVersion"] = str(rv)
        self.events.append(
            {
                "rv": rv,
                "key": key,
                "ns": ns,
                "name": name,
                "type": etype,
                "object": copy,
            }
        )


def _match_label_selector(obj: dict, selector: str) -> bool:
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    for clause in selector.split(","):
        if not clause:
            continue
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            if labels.get(k) == v:
                return False
        elif "=" in clause:
            k, v = clause.split("=", 1)
            if labels.get(k) != v:
                return False
        elif clause not in labels:
            return False
    return True


def _field_get(obj: dict, dotted: str):
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _match_field_selector(obj: dict, selector: str) -> bool:
    for clause in selector.split(","):
        if not clause:
            continue
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            if str(_field_get(obj, k)) == v:
                return False
        elif "==" in clause:
            k, v = clause.split("==", 1)
            if str(_field_get(obj, k)) != v:
                return False
        elif "=" in clause:
            k, v = clause.split("=", 1)
            if str(_field_get(obj, k)) != v:
                return False
    return True


class FakeKubeServer:
    """Runs the API server on 127.0.0.1:<port> in a daemon thread."""

    def __init__(self):
        self.state = _State()
        state = self.state

        class Handler(BaseHTTPRequestHandler):
            # silence request logging
            def log_message(self, *a):
                pass

            def _send(self, code: int, body: dict):
                raw = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _error(self, code: int, msg: str):
                self._send(code, {"kind": "Status", "code": code,
                                  "message": msg})

            def _route(self):
                parsed = urllib.parse.urlparse(self.path)
                m = _PATH_RE.match(parsed.path)
                if not m:
                    return None
                gv = m.group("core_ver") or (
                    f"{m.group('group')}/{m.group('ver')}"
                )
                key = (gv, m.group("plural"))
                if key not in ROUTES:
                    return None
                params = dict(urllib.parse.parse_qsl(parsed.query))
                return key, m.group("ns"), m.group("name"), m.group("sub"), params

            def _read_body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def do_GET(self):
                r = self._route()
                if r is None:
                    return self._error(404, f"no route {self.path}")
                key, ns, name, _, params = r
                if not name and params.get("watch") == "true":
                    return self._watch(key, ns, params)
                with state.lock:
                    store = state.objects[key]
                    if name:
                        obj = store.get((ns or "", name))
                        if obj is None:
                            return self._error(404, f"{name} not found")
                        return self._send(200, obj)
                    state.list_counts[key] = state.list_counts.get(key, 0) + 1
                    items = [
                        o for (ons, _), o in sorted(store.items())
                        if ns is None or ons == ns
                    ]
                    if "labelSelector" in params:
                        items = [o for o in items
                                 if _match_label_selector(o, params["labelSelector"])]
                    if "fieldSelector" in params:
                        items = [o for o in items
                                 if _match_field_selector(o, params["fieldSelector"])]
                    return self._send(200, {
                        "kind": ROUTES[key] + "List",
                        "metadata": {"resourceVersion": str(state.rv)},
                        "items": items,
                    })

            def _watch(self, key, ns, params):
                """Streaming watch: line-delimited JSON events with
                rv > resourceVersion, held open for timeoutSeconds
                (the real API-server contract the client resumes on)."""
                since = int(params.get("resourceVersion") or 0)
                timeout = float(params.get("timeoutSeconds") or 30)
                # real API servers only send BOOKMARK when the client
                # opted in — mirror that so a client that forgets the
                # param fails the quiet-period resume tests
                bookmarks_on = params.get("allowWatchBookmarks") == "true"
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Connection", "close")
                self.end_headers()
                deadline = time.monotonic() + timeout
                sent = since
                last_bookmark = time.monotonic()
                try:
                    while time.monotonic() < deadline:
                        with state.lock:
                            # etcd-compaction semantics: a resume point
                            # older than the journal head is GONE — the
                            # server answers with a 410 ERROR event and
                            # the client must relist (k8s contract)
                            if state.events and sent < state.events[0]["rv"] - 1:
                                line = json.dumps({
                                    "type": "ERROR",
                                    "object": {
                                        "kind": "Status",
                                        "code": 410,
                                        "reason": "Expired",
                                        "message": (
                                            f"too old resource version: "
                                            f"{sent}"
                                        ),
                                    },
                                })
                                self.wfile.write(line.encode() + b"\n")
                                self.wfile.flush()
                                return
                            pending = [
                                e for e in state.events
                                if e["rv"] > sent
                                and e["key"] == key
                                and (ns is None or e["ns"] == ns)
                            ]
                            # snapshot the head INSIDE the lock: a
                            # bookmark may only skip rvs whose events
                            # were visible to this pending scan
                            head = state.rv
                        for e in pending:
                            line = json.dumps(
                                {"type": e["type"], "object": e["object"]}
                            )
                            self.wfile.write(line.encode() + b"\n")
                            sent = max(sent, e["rv"])
                        # periodic BOOKMARK (k8s allowWatchBookmarks):
                        # advances the client's resume point through
                        # quiet periods and through events of OTHER
                        # routes, so a reconnect doesn't start from a
                        # compactable rv
                        if bookmarks_on and time.monotonic() - last_bookmark > 0.2:
                            if head > sent:
                                line = json.dumps({
                                    "type": "BOOKMARK",
                                    "object": {
                                        "metadata": {
                                            "resourceVersion": str(head)
                                        }
                                    },
                                })
                                self.wfile.write(line.encode() + b"\n")
                                sent = max(sent, head)
                            last_bookmark = time.monotonic()
                        # heartbeat (clients skip blank lines): makes a
                        # dead client raise BrokenPipe so the handler
                        # exits instead of idling out the whole window
                        self.wfile.write(b"\n")
                        self.wfile.flush()
                        time.sleep(0.02)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away

            def do_POST(self):
                r = self._route()
                if r is None:
                    return self._error(404, f"no route {self.path}")
                key, ns, _, _, _ = r
                obj = self._read_body()
                meta = obj.setdefault("metadata", {})
                oname = meta.get("name")
                if not oname:
                    return self._error(422, "metadata.name required")
                ons = meta.setdefault("namespace", ns or "default")
                with state.lock:
                    store = state.objects[key]
                    if (ons, oname) in store:
                        return self._error(409, f"{oname} already exists")
                    meta["resourceVersion"] = state.next_rv()
                    obj.setdefault("status", {})
                    store[(ons, oname)] = obj
                    state.record(key, ons, oname, "ADDED", obj)
                    return self._send(201, obj)

            def do_PUT(self):
                r = self._route()
                if r is None:
                    return self._error(404, f"no route {self.path}")
                key, ns, name, sub, _ = r
                if not name:
                    return self._error(405, "PUT needs a name")
                body = self._read_body()
                with state.lock:
                    store = state.objects[key]
                    cur = store.get((ns or "", name))
                    if cur is None:
                        return self._error(404, f"{name} not found")
                    rv = body.get("metadata", {}).get("resourceVersion")
                    if rv and rv != cur["metadata"]["resourceVersion"]:
                        return self._error(409, "resourceVersion conflict")
                    if sub == "status":
                        cur["status"] = body.get("status", {})
                    else:
                        body["metadata"]["resourceVersion"] = state.next_rv()
                        body["metadata"].setdefault("namespace", ns or "default")
                        store[(ns or "", name)] = body
                        cur = body
                    state.record(key, ns or "", name, "MODIFIED", cur)
                    return self._send(200, cur)

            def do_PATCH(self):
                r = self._route()
                if r is None:
                    return self._error(404, f"no route {self.path}")
                key, ns, name, sub, _ = r
                if not name:
                    return self._error(405, "PATCH needs a name")
                patch = self._read_body()
                with state.lock:
                    store = state.objects[key]
                    cur = store.get((ns or "", name))
                    if cur is None:
                        return self._error(404, f"{name} not found")
                    rv = patch.get("metadata", {}).get("resourceVersion")
                    if rv is not None and rv != cur["metadata"]["resourceVersion"]:
                        return self._error(409, "resourceVersion conflict")

                    def merge(dst, src):
                        for k, v in src.items():
                            if k == "resourceVersion":
                                continue
                            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                                merge(dst[k], v)
                            elif v is None:
                                dst.pop(k, None)
                            else:
                                dst[k] = v

                    if sub == "status":
                        merge(cur.setdefault("status", {}),
                              patch.get("status", {}))
                    else:
                        merge(cur, patch)
                        cur["metadata"]["resourceVersion"] = state.next_rv()
                    state.record(key, ns or "", name, "MODIFIED", cur)
                    return self._send(200, cur)

            def do_DELETE(self):
                r = self._route()
                if r is None:
                    return self._error(404, f"no route {self.path}")
                key, ns, name, _, _ = r
                if not name:
                    return self._error(405, "DELETE needs a name")
                with state.lock:
                    store = state.objects[key]
                    obj = store.pop((ns or "", name), None)
                    if obj is None:
                        return self._error(404, f"{name} not found")
                    state.record(key, ns or "", name, "DELETED", obj)
                    # cascade: Job deletion removes its pods (the k8s GC
                    # analog; KubeCluster passes propagationPolicy)
                    if key == ("batch/v1", "jobs"):
                        pods = state.objects[("v1", "pods")]
                        for pkey in [
                            k for k, p in pods.items()
                            if p["metadata"].get("labels", {}).get("job-name")
                            == name
                        ]:
                            pods.pop(pkey)
                    return self._send(200, {"kind": "Status", "status": "Success"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start_reconciler(self, interval_s: float = 0.02) -> None:
        """Continuously reconcile pods in the background (the kubelet /
        Job-controller stand-in for tests driving the real CLI loop)."""
        self._reconcile_stop = threading.Event()

        def _loop():
            while not self._reconcile_stop.is_set():
                self.reconcile_pods()
                self._reconcile_stop.wait(interval_s)

        threading.Thread(target=_loop, daemon=True).start()

    # -- world building ----------------------------------------------------

    def add_node(self, name: str, cpu: str = "96", memory: str = "384Gi",
                 tpu: int = 8, labels: Optional[dict] = None) -> None:
        with self.state.lock:
            alloc = {"cpu": cpu, "memory": memory}
            if tpu:
                alloc["google.com/tpu"] = tpu
            self.state.objects[("v1", "nodes")][("", name)] = {
                "kind": "Node",
                "metadata": {
                    "name": name,
                    "namespace": "",
                    "labels": labels or {},
                    "resourceVersion": self.state.next_rv(),
                },
                "status": {"allocatable": alloc},
            }

    def reconcile_pods(self, phase: str = "Running") -> int:
        """Grow each Job's pods to its parallelism (the kubelet/Job
        controller stand-in). Returns pods created."""
        created = 0
        with self.state.lock:
            # deployments become ready (coordinator await,
            # reference: createResource polls ReadyReplicas==Replicas)
            for dep in self.state.objects[("apps/v1", "deployments")].values():
                replicas = int(dep.get("spec", {}).get("replicas", 1))
                dep.setdefault("status", {})["readyReplicas"] = replicas
            jobs = self.state.objects[("batch/v1", "jobs")]
            pods = self.state.objects[("v1", "pods")]
            nodes = list(self.state.objects[("v1", "nodes")])
            for (ns, jname), job in jobs.items():
                want = int(job.get("spec", {}).get("parallelism", 0))
                labels = dict(
                    job["spec"]["template"]["metadata"].get("labels", {})
                )
                labels["job-name"] = jname
                tmpl = job["spec"]["template"]["spec"]
                def _idx(key):
                    # numeric suffix ordering: job-10 > job-9
                    return int(key[1].rsplit("-", 1)[1])

                have = sorted(
                    (
                        k for k, p in pods.items()
                        if p["metadata"].get("labels", {}).get("job-name")
                        == jname
                    ),
                    key=_idx,
                )
                # scale down: delete surplus (highest index first)
                for k in have[want:]:
                    pods.pop(k)
                have = have[:want]
                next_idx = _idx(have[-1]) + 1 if have else 0
                for i in range(next_idx, next_idx + want - len(have)):
                    pname = f"{jname}-{i}"
                    node = nodes[i % len(nodes)][1] if nodes else ""
                    pods[(ns, pname)] = {
                        "kind": "Pod",
                        "metadata": {
                            "name": pname,
                            "namespace": ns,
                            "labels": dict(labels),
                            "resourceVersion": self.state.next_rv(),
                        },
                        "spec": {
                            "nodeName": node,
                            "containers": tmpl["containers"],
                        },
                        "status": {"phase": phase},
                    }
                    created += 1
                job.setdefault("status", {})["active"] = want
        return created

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self.state.lock:
            self.state.objects[("v1", "pods")][(namespace, name)]["status"][
                "phase"
            ] = phase

    def create_training_job(self, manifest: dict) -> None:
        key = ("edl-tpu.org/v1", "trainingjobs")
        with self.state.lock:
            meta = manifest.setdefault("metadata", {})
            ns = meta.setdefault("namespace", "default")
            meta["resourceVersion"] = self.state.next_rv()
            manifest.setdefault("status", {})
            existed = (ns, meta["name"]) in self.state.objects[key]
            self.state.objects[key][(ns, meta["name"])] = manifest
            self.state.record(
                key, ns, meta["name"],
                "MODIFIED" if existed else "ADDED", manifest,
            )

    def delete_training_job(self, namespace: str, name: str) -> None:
        key = ("edl-tpu.org/v1", "trainingjobs")
        with self.state.lock:
            obj = self.state.objects[key].pop((namespace, name), None)
            if obj is not None:
                self.state.record(key, namespace, name, "DELETED", obj)

    def list_count(self, gv: str = "edl-tpu.org/v1",
                   plural: str = "trainingjobs") -> int:
        """Non-watch LIST hits for a route — proves a streaming watcher
        is not relisting per tick."""
        with self.state.lock:
            return self.state.list_counts.get((gv, plural), 0)

    def get_object(self, gv: str, plural: str, namespace: str, name: str):
        with self.state.lock:
            obj = self.state.objects[(gv, plural)].get((namespace, name))
            return json.loads(json.dumps(obj)) if obj else None

    def close(self):
        if getattr(self, "_reconcile_stop", None) is not None:
            self._reconcile_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
