"""P2P shard transfer (runtime/shard_server.py) — the reshard data
plane that moves owner-changing state worker-to-worker across the drain
window instead of through shared storage (VERDICT r3 #5)."""

import numpy as np
import pytest

from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.runtime.checkpoint import LocalSnapshot, _piece_key
from edl_tpu.runtime.shard_server import (
    RemotePieces,
    ShardServer,
    _Conn,
    fetch_index,
)


def _snap(step, pieces):
    shapes = {
        k: tuple(
            max(o[i] + a.shape[i] for o, a in plist)
            for i in range(plist[0][1].ndim)
        )
        for k, plist in pieces.items()
    }
    return LocalSnapshot(
        step=step,
        pieces=pieces,
        primary={k: [o for o, _ in v] for k, v in pieces.items()},
        shapes=shapes,
        dtypes={
            k: str(plist[0][1].dtype) for k, plist in pieces.items()
        },
    )


def test_server_index_and_fetch_roundtrip():
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    snap = _snap(5, {"p:w": [((0, 0), w)]})
    srv = ShardServer(lambda: snap)
    try:
        step, entries = fetch_index(f"127.0.0.1:{srv.port}")
        assert step == 5
        assert set(entries) == {_piece_key("p:w", (0, 0), (3, 4))}
        rp = RemotePieces(f"127.0.0.1:{srv.port}", entries)
        got = rp[next(iter(entries))]
        np.testing.assert_array_equal(got, w)
        # unknown piece is a clean KeyError (not a hang/hole)
        with pytest.raises(KeyError):
            rp[_piece_key("p:missing", (0,), (4,))]
        rp.close()
    finally:
        srv.close()


def test_server_follows_snapshot_swap():
    """The server serves whatever the owner's CURRENT snapshot is —
    reshard updates are visible without replumbing."""
    holder = {"snap": None}
    srv = ShardServer(lambda: holder["snap"])
    try:
        step, entries = fetch_index(f"127.0.0.1:{srv.port}")
        assert step == -1 and entries == {}
        holder["snap"] = _snap(9, {"p:b": [((0,), np.ones(4, np.int64))]})
        step, entries = fetch_index(f"127.0.0.1:{srv.port}")
        assert step == 9 and len(entries) == 1
    finally:
        srv.close()


def test_batched_get_many_roundtrip():
    """FETCHN pipelining + the connection pool return the same bytes
    as piece-at-a-time fetches; cache makes repeat access free."""
    rs = np.random.RandomState(1)
    pieces = {
        "p:w": [((i * 16, 0), rs.rand(16, 8).astype(np.float32)) for i in range(6)]
    }
    snap = _snap(2, pieces)
    srv = ShardServer(lambda: snap)
    try:
        _, entries = fetch_index(f"127.0.0.1:{srv.port}")
        assert len(entries) == 6
        rp = RemotePieces(f"127.0.0.1:{srv.port}", entries, nconn=3)
        got = rp.get_many(list(entries))
        assert set(got) == set(entries)
        for off, arr in pieces["p:w"]:
            np.testing.assert_array_equal(
                got[_piece_key("p:w", off, arr.shape)], arr
            )
        # single-item access is now a cache hit (no network)
        srv.close()
        one = next(iter(entries))
        np.testing.assert_array_equal(rp[one], got[one])
        rp.close()
    finally:
        srv.close()


def test_get_many_missing_piece_raises():
    snap = _snap(2, {"p:w": [((0, 0), np.ones((4, 4), np.float32))]})
    srv = ShardServer(lambda: snap)
    try:
        _, entries = fetch_index(f"127.0.0.1:{srv.port}")
        rp = RemotePieces(
            f"127.0.0.1:{srv.port}",
            dict(entries, **{_piece_key("p:gone", (0,), (4,)): "float32"}),
            nconn=1,
        )
        with pytest.raises(KeyError):
            rp.get_many(
                list(entries) + [_piece_key("p:gone", (0,), (4,))]
            )
        rp.close()
    finally:
        srv.close()


def test_token_auth_gates_weights():
    """A server given a token check serves ONLY authed connections:
    wrong/absent token gets nothing (the weight plane is gated by
    'can read the job KV', not 'can reach the port')."""
    snap = _snap(7, {"p:w": [((0,), np.ones(4, np.float32))]})
    srv = ShardServer(lambda: snap, check_token=lambda t: t == "s3cret")
    addr = f"127.0.0.1:{srv.port}"
    try:
        assert fetch_index(addr) is None  # no token: rejected
        assert fetch_index(addr, token="wrong") is None
        got = fetch_index(addr, token="s3cret")
        assert got is not None and got[0] == 7
        _, entries = got
        # fetches honor the same gate
        bad = RemotePieces(addr, entries, token="wrong", nconn=1)
        with pytest.raises(OSError):
            bad[next(iter(entries))]
        bad.close()
        good = RemotePieces(addr, entries, token="s3cret", nconn=1)
        np.testing.assert_array_equal(
            good[next(iter(entries))], np.ones(4, np.float32)
        )
        good.close()
    finally:
        srv.close()


def test_peer_coverage_geometry():
    import jax

    from edl_tpu.train.trainer import TrainState

    import optax

    params = {"w": np.zeros((4, 4), np.float32)}
    like = jax.eval_shape(
        lambda: TrainState.create(params, optax.sgd(0.1))
    )
    full = [
        _piece_key("p:w", (0, 0), (2, 4)),
        _piece_key("p:w", (2, 0), (2, 4)),
    ]
    opt_keys = [
        k
        for k, _ in ckpt._state_leaf_items(like)
        if k.startswith("o:")
    ]
    full += [_piece_key(k, (0, 0), (4, 4)) for k in opt_keys]
    assert ckpt.peer_coverage_ok(like, full)
    # replicas at the same offset dedupe, not double-count
    assert ckpt.peer_coverage_ok(like, full + full)
    # a missing tile fails the check
    assert not ckpt.peer_coverage_ok(like, full[1:])

    opt_full = [_piece_key(k, (0, 0), (4, 4)) for k in opt_keys]
    # OVERLAPPING pieces at misaligned offsets (same-step snapshots from
    # two different world layouts): rows 0-2 and rows 1-3 overlap on
    # rows 1-2 and sum to 24 >= 16 elements while leaving row 3 bare —
    # an element-count check would wrongly pass this
    holey = [
        _piece_key("p:w", (0, 0), (3, 4)),
        _piece_key("p:w", (1, 0), (2, 4)),
    ] + opt_full
    assert not ckpt.peer_coverage_ok(like, holey)
    # ... while a misaligned overlap whose union truly tiles passes
    tiled = [
        _piece_key("p:w", (0, 0), (3, 4)),
        _piece_key("p:w", (1, 0), (3, 4)),
    ] + opt_full
    assert ckpt.peer_coverage_ok(like, tiled)
    # mixed-axis layouts: row-cut ∪ column-cut with one column piece
    # missing covers >16 elements but not column 2-3 of rows 2-3
    cross_hole = [
        _piece_key("p:w", (0, 0), (2, 4)),
        _piece_key("p:w", (0, 0), (4, 2)),
    ] + opt_full
    assert not ckpt.peer_coverage_ok(like, cross_hole)
    assert ckpt.peer_coverage_ok(
        like,
        cross_hole + [_piece_key("p:w", (2, 2), (2, 2))],
    )


def test_coverage_ignores_rank_mismatched_entries():
    """A stale/version-skewed peer advertising geometry of the wrong
    rank is non-contributing — the decision degrades to disk, never an
    IndexError in rank 0's decision loop."""
    import jax
    import optax

    from edl_tpu.train.trainer import TrainState

    params = {"w": np.zeros((4, 4), np.float32)}
    like = jax.eval_shape(
        lambda: TrainState.create(params, optax.sgd(0.1))
    )
    opt_keys = [
        k for k, _ in ckpt._state_leaf_items(like) if k.startswith("o:")
    ]
    base = [_piece_key(k, (0, 0), (4, 4)) for k in opt_keys]
    # 1-D geometry against a 2-D leaf: ignored, not a crash
    assert not ckpt.peer_coverage_ok(
        like, base + [_piece_key("p:w", (0,), (16,))]
    )
    assert ckpt.peer_coverage_ok(
        like,
        base
        + [_piece_key("p:w", (0,), (16,)), _piece_key("p:w", (0, 0), (4, 4))],
    )


def test_p2p_veto_per_step_semantics():
    """Veto bookkeeping is one KV key per step with a TTL — blind
    writes for different steps never race, so no lost-update can
    resurrect a doomed step, and expiry unblocks after the TTL."""
    from edl_tpu.runtime.p2p_restore import _VETO_TTL_EPOCHS, _veto_active

    assert _veto_active("3", epoch=3)
    assert _veto_active("3", epoch=3 + _VETO_TTL_EPOCHS)
    assert not _veto_active("3", epoch=4 + _VETO_TTL_EPOCHS)
    # unset / malformed reads as no veto
    assert not _veto_active(None, epoch=1)
    assert not _veto_active("", epoch=1)
    assert not _veto_active("garbage", epoch=1)


def test_piece_index_drops_rank_skewed_remote_entries():
    """The same rank filter applies at ASSEMBLY time: a skewed entry
    that slipped past decision (or arrived between decision and
    assembly) is dropped at _PieceIndex construction, so it can neither
    crash the box math nor be zip-truncated into the overlap test."""

    class FakeRemote:
        def __init__(self, entries):
            self._e = entries

        def entries(self):
            return list(self._e)

        def __getitem__(self, entry):  # pragma: no cover - never fetched
            raise AssertionError("skewed entry must never be fetched")

    skew = FakeRemote([_piece_key("p:w", (0,), (16,))])
    good = np.arange(16, dtype=np.float32).reshape(4, 4)
    snap = _snap(1, {"p:w": [((0, 0), good)]})
    idx = ckpt._PieceIndex(
        None, snap, remotes=[skew], shapes={"p:w": (4, 4)}
    )
    got = idx.assemble("p:w", (slice(0, 4), slice(0, 4)), (4, 4), np.float32)
    np.testing.assert_array_equal(got, good)


def test_boxes_tile_unit():
    """Direct geometry unit: _boxes_tile is a true box union."""
    assert ckpt._boxes_tile((4,), [((0,), (2,)), ((2,), (2,))])
    assert not ckpt._boxes_tile((4,), [((0,), (2,)), ((3,), (1,))])
    # overlap does not double-count
    assert not ckpt._boxes_tile((4,), [((0,), (3,)), ((1,), (2,))])
    assert ckpt._boxes_tile((4,), [((0,), (3,)), ((1,), (3,))])
    # scalar leaves: any piece covers, none does not
    assert ckpt._boxes_tile((), [((), ())])
    assert not ckpt._boxes_tile((), [])
    # 3-d cross-cut hole
    assert not ckpt._boxes_tile(
        (2, 2, 2),
        [((0, 0, 0), (1, 2, 2)), ((0, 0, 0), (2, 2, 1)), ((1, 0, 1), (1, 1, 1))],
    )
    assert ckpt._boxes_tile(
        (2, 2, 2),
        [
            ((0, 0, 0), (1, 2, 2)),
            ((0, 0, 0), (2, 2, 1)),
            ((1, 0, 1), (1, 1, 1)),
            ((1, 1, 1), (1, 1, 1)),
        ],
    )


def test_assemble_rejects_overlap_hole():
    """The assemble-time check agrees with the decision check: pieces
    that overlap their way past the element total still raise on the
    genuine hole instead of returning uninitialized memory."""
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    snap = _snap(
        3,
        {
            "p:w": [
                ((0, 0), a),                      # rows 0-2
                ((1, 0), np.arange(4, 16, dtype=np.float32).reshape(3, 4)),  # rows 1-3
            ]
        },
    )
    # leaf is 5 rows total; rows 0-3 covered, row 4 is a hole although
    # 12 + 12 = 24 > 20 elements
    idx = ckpt._PieceIndex(None, snap)
    with pytest.raises(ValueError, match="hole|coverage"):
        idx.assemble("p:w", (slice(0, 5), slice(0, 4)), (5, 4), np.float32)
    # the covered sub-slice still assembles fine, overlap bytes agree
    got = idx.assemble("p:w", (slice(0, 4), slice(0, 4)), (5, 4), np.float32)
    np.testing.assert_array_equal(got[:3], a)
    assert got.shape == (4, 4)


def test_pure_peer_restore_reassembles_state(cpu_devices):
    """load_from_pieces with ONLY remote sources (no manifest, no local
    RAM) rebuilds the exact state on a new mesh — the disjoint-worker
    migration in miniature: two 'old workers' each serve half the fsdp
    shards; the 'new worker' assembles both halves over TCP."""
    import jax
    import optax

    from edl_tpu.parallel import sharding as shd
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.train.trainer import TrainState, shard_state, state_pspecs

    params = {
        "w": np.arange(64, dtype=np.float32).reshape(8, 8),
        "b": np.arange(8, dtype=np.float32),
    }
    tx = optax.adam(1e-2)
    plan = MeshPlan.create(fsdp=4)
    mesh = plan.build(cpu_devices[:4])
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    snap = ckpt.snapshot_local(state)

    # split the pieces across two virtual old workers by offset parity
    def half(i):
        pieces = {}
        for key, plist in snap.pieces.items():
            mine = [p for j, p in enumerate(sorted(plist)) if j % 2 == i]
            if mine:
                pieces[key] = mine
        return LocalSnapshot(
            step=snap.step, pieces=pieces, primary={},
            shapes=snap.shapes, dtypes=snap.dtypes,
        )

    servers = [ShardServer(lambda h=half(i): h) for i in range(2)]
    try:
        remotes = []
        for srv in servers:
            step, entries = fetch_index(f"127.0.0.1:{srv.port}")
            assert step == snap.step
            remotes.append(RemotePieces(f"127.0.0.1:{srv.port}", entries))
        # coverage across BOTH halves holds; either alone does not
        like = jax.eval_shape(lambda: TrainState.create(params, tx))
        both = [e for r in remotes for e in r.entries()]
        assert ckpt.peer_coverage_ok(like, both)
        assert not ckpt.peer_coverage_ok(like, list(remotes[0].entries()))

        new_plan = MeshPlan.create(dp=2)
        new_mesh = new_plan.build(cpu_devices[4:6])
        new_sh = shd.named(state_pspecs(like, new_plan, None), new_mesh)
        restored = ckpt.load_from_pieces(
            snap.step, like, new_sh, remotes=remotes
        )
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), params["w"]
        )
        np.testing.assert_array_equal(
            np.asarray(restored.params["b"]), params["b"]
        )
        assert int(restored.step) == snap.step
        for r in remotes:
            r.close()
    finally:
        for srv in servers:
            srv.close()


def test_conn_close_waits_for_inflight_batch():
    """_Conn.close() takes the connection lock (`edl check`
    lockset-race finding): a teardown racing an in-flight fetch_batch
    must not None the socket/file out from under a blocked read — it
    waits for the batch to finish instead."""
    import threading
    import time as _time

    conn = _Conn("127.0.0.1:1", token=None)
    conn.lock.acquire()  # simulate fetch_batch mid-flight on another thread
    closed = threading.Event()

    def do_close():
        conn.close()
        closed.set()

    t = threading.Thread(target=do_close, daemon=True)
    t.start()
    _time.sleep(0.05)
    assert not closed.is_set()  # close is waiting behind the batch
    conn.lock.release()
    assert closed.wait(2.0)
    assert conn.sock is None and conn.file is None


def test_conn_close_during_parallel_get_many_is_clean():
    """End-to-end teardown race: threads drain get_many stripes while
    another thread closes the pool. The only acceptable outcomes are
    full results or connection errors — never an AttributeError from a
    half-torn _Conn."""
    import threading

    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    snap = _snap(3, {"p:w": [((0, 0), w)]})
    srv = ShardServer(lambda: snap)
    entry = _piece_key("p:w", (0, 0), (8, 8))
    oddities = []

    for _ in range(5):
        rp = RemotePieces(
            f"127.0.0.1:{srv.port}", {entry: "float32"}, nconn=2
        )

        def fetch():
            try:
                rp.get_many([entry])
            except (OSError, ValueError, KeyError):
                pass  # torn by close: expected outcome
            except AttributeError as e:  # half-torn connection state
                oddities.append(e)

        ts = [threading.Thread(target=fetch) for _ in range(3)]
        closer = threading.Thread(target=rp.close)
        for t in ts:
            t.start()
        closer.start()
        for t in ts:
            t.join(10)
        closer.join(10)
        rp.close()
    srv.close()
    assert not oddities
