"""Examples stay runnable: manifests validate, demos execute end-to-end.

The reference validates its examples only by hand (SURVEY §4 — manual
minikube walkthroughs); here they are part of the suite.
"""

import glob
import os
import sys

import pytest

from edl_tpu.api.job import TrainingJob
from edl_tpu.api.parser import JobParser

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(ROOT, "examples")


def _run_example(monkeypatch, relpath, argv):
    path = os.path.join(EXAMPLES, relpath)
    monkeypatch.setattr(sys, "argv", [path] + argv)
    monkeypatch.syspath_prepend(os.path.dirname(path))
    import importlib.util

    name = "example_" + relpath.replace("/", "_").replace(".py", "")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main()


def test_all_manifests_validate():
    manifests = glob.glob(os.path.join(EXAMPLES, "*", "job.yaml"))
    assert len(manifests) >= 3
    for m in manifests:
        job = TrainingJob.from_yaml_file(m)
        JobParser().validate(job)
        assert job.name


def test_elastic_demo_squeeze(monkeypatch, capsys):
    assert _run_example(monkeypatch, "elastic_demo.py", []) == 0
    out = capsys.readouterr().out
    assert "squeeze complete" in out


def test_fit_a_line_train_ft_kill_worker(monkeypatch, capsys, cpu_devices):
    assert (
        _run_example(
            monkeypatch,
            "fit_a_line/train_ft.py",
            ["--kill-one-worker", "--samples", "1024", "--chunk", "64"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "phase=succeeded" in out


def test_fit_a_line_train_local(monkeypatch, capsys, tmp_path):
    assert (
        _run_example(
            monkeypatch,
            "fit_a_line/train_local.py",
            ["--samples", "512", "--passes", "1", "--save-dir", str(tmp_path)],
        )
        == 0
    )
    assert "pass 0" in capsys.readouterr().out
    assert list(tmp_path.glob("*.npz"))


def test_ctr_train(monkeypatch, capsys, cpu_devices):
    """The classic elastic CTR demo, on REAL rows by default in the
    suite (VERDICT r4 missing #2: the headline workload must not train
    on noise)."""
    pytest.importorskip("sklearn")
    assert (
        _run_example(
            monkeypatch,
            "ctr/train.py",
            ["--steps", "6", "--batch", "16", "--real-data"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "REAL rows" in out and "trained 6 steps" in out


@pytest.mark.multiproc  # launches real worker subprocesses
def test_ctr_real_data_elastic_auc(monkeypatch, capsys, tmp_path):
    """REAL CTR rows end-to-end (VERDICT r4 missing #2): genuine
    clinical rows in Criteo format through the shard pipeline, an
    elastic multi-process job scaling 1 -> 2 mid-pass, the in-job
    held-out AUC published per export, and the final export re-scored
    through the `edl predict` consumer — asserted > 0.85 inside the
    example (a model of the world, not of noise)."""
    pytest.importorskip("sklearn")
    assert (
        _run_example(
            monkeypatch,
            "ctr/real_data.py",
            ["--workdir", str(tmp_path), "--passes", "4"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "real training rows" in out
    assert "held-out AUC" in out
    import json

    man = json.load(open(tmp_path / "data" / "manifest.json"))
    assert man["n_samples"] > 400
    assert sorted(man["keys"]) == ["dense", "label", "sparse"]


def test_llama_fsdp_train(monkeypatch, capsys, cpu_devices):
    assert (
        _run_example(monkeypatch, "llama/train.py", ["--steps", "2", "--seq", "32"])
        == 0
    )
    assert "ok" in capsys.readouterr().out


def test_recognize_digits_static_shards(monkeypatch, capsys, cpu_devices):
    assert (
        _run_example(
            monkeypatch,
            "recognize_digits/train.py",
            ["--samples", "512", "--epochs", "1", "--per-worker-batch", "16"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "phase=succeeded" in out
    assert "fixed 4 workers" in out


@pytest.mark.multiproc  # launches real worker subprocesses
def test_bert_elastic_pretrain(monkeypatch, capsys):
    """BASELINE config #4: BERT-class elastic DP with checkpoint
    reshard, through the real multi-process runtime with one scale-up."""
    assert (
        _run_example(
            monkeypatch,
            "bert/train.py",
            ["--samples", "512", "--seq-len", "24", "--step-sleep", "0.3"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "phase=succeeded" in out and "reshards=1" in out


@pytest.mark.multiproc  # launches real worker subprocesses
def test_resnet_elastic_train(monkeypatch, capsys):
    """BASELINE config #3: ResNet-class elastic all-reduce DP with a
    graceful mid-run scale-down drain."""
    assert (
        _run_example(
            monkeypatch,
            "resnet/train.py",
            ["--samples", "1024"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "phase=succeeded" in out and "reshards=1" in out


@pytest.mark.multiproc  # launches real worker subprocesses
def test_moe_elastic_pretrain(monkeypatch, capsys):
    """Expert parallelism as a workload (no reference analog): MoE
    decoder on an ep=2,dp mesh through the multi-process runtime; the
    mid-run scale-up grows dp while the pinned expert axis survives."""
    assert (
        _run_example(
            monkeypatch,
            "moe/train.py",
            ["--samples", "512", "--seq-len", "24", "--step-sleep", "0.3"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "phase=succeeded" in out and "reshards=1" in out


@pytest.mark.multiproc  # launches real worker subprocesses
def test_fit_a_line_real_data(monkeypatch, capsys, tmp_path):
    """REAL public data through the shard pipeline (VERDICT r3 missing
    #2): the bundled diabetes dataset is prepared into runtime/shards
    format, an elastic multi-process job trains from it via the lease
    queue, the commit leader publishes a held-out eval metric per
    export, and the final export beats predict-the-mean on the real
    test split."""
    pytest.importorskip("sklearn")
    assert (
        _run_example(
            monkeypatch,
            "fit_a_line/real_data.py",
            ["--workdir", str(tmp_path), "--passes", "3"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "real training rows" in out
    assert "test RMSE" in out
    # the prepared dataset is a valid shard dir with a manifest
    import json

    man = json.load(open(tmp_path / "data" / "manifest.json"))
    assert man["n_samples"] > 300 and man["keys"] == ["x", "y"]


def test_recognize_digits_real_data(monkeypatch, capsys, cpu_devices):
    """The digits example on REAL handwritten data (scikit-learn's
    bundled 8x8 digits — the MNIST-class analog of the reference's
    recognize_digits): static-shard mode, per-epoch checkpoints, and a
    held-out accuracy that clears chance by 5x (asserted > 0.5 inside
    the example)."""
    pytest.importorskip("sklearn")
    assert (
        _run_example(
            monkeypatch,
            "recognize_digits/train.py",
            ["--real-data", "--epochs", "12"],
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "real digits" in out and "test_acc" in out
