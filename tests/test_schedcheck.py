"""`edl schedcheck` dynamic verification (edl_tpu/analysis/sched.py +
hb.py + harnesses.py): sync-shim fidelity (on-semantics == stdlib,
off == byte-for-byte stdlib objects), deterministic seeded exploration
with exact replay, the vector-clock happens-before detector, deadlock
detection, the PR 7 mutation regression corpus, and the CLI verb.
jax-free — the whole checker is pure stdlib threading."""

import json
import queue
import threading
import time

import pytest

from edl_tpu.analysis import harnesses as H
from edl_tpu.analysis import hb, sched
from edl_tpu.cli.main import main as cli_main


@pytest.fixture(autouse=True, scope="module")
def _quiet_logs():
    """Harnesses drive real error paths (pusher publish failures, conn
    teardown) whose warn/error logs are noise here — evidence is
    reported through the explorer."""
    import logging

    prev = logging.root.manager.disable
    logging.disable(logging.ERROR)
    H.warm_globals()  # singletons built with REAL locks, pre-shim
    yield
    logging.disable(prev)


# ---------------------------------------------------------------------------
# shim fidelity (satellite: shim-on == stdlib semantics, shim-off == stdlib)


def _stdlib_identity_ok():
    return (
        threading.Lock is sched._REAL["Lock"]
        and threading.RLock is sched._REAL["RLock"]
        and threading.Condition is sched._REAL["Condition"]
        and threading.Event is sched._REAL["Event"]
        and threading.Thread is sched._REAL["Thread"]
        and queue.Queue is sched._REAL["Queue"]
        and time.sleep is sched._REAL["sleep"]
    )


def test_shim_off_is_byte_for_byte_stdlib():
    """Zero overhead when not checking: with no scheduler active, the
    names in threading/queue/time are the very same objects captured
    at import — not wrappers."""
    assert _stdlib_identity_ok()


def _counter_harness(sink):
    """Lock-free shared counter: two shim threads, interleaved bumps.
    Python-level += on a dict slot is one uninstrumented op, so every
    schedule must agree with the plain stdlib run."""

    def h():
        state = {"n": 0}

        def worker():
            for _ in range(5):
                state["n"] += 1

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        sink.append(state["n"])

    return h


def test_shim_on_semantics_match_stdlib_over_50_seeds():
    ref_sink = []
    _counter_harness(ref_sink)()  # plain stdlib run, no scheduler
    assert ref_sink == [10]

    got = []
    h = _counter_harness(got)
    for k in range(50):
        res = sched.run_one(h, seed=k)
        assert res.failure is None, res.failure
    assert got == [10] * 50

    # and the shim tore down cleanly every time
    assert _stdlib_identity_ok()


# ---------------------------------------------------------------------------
# determinism, replay, exploration


def _racy_harness():
    class Obj:
        pass

    o = Obj()
    o.x = 0
    sched.instrument(o, ["x"], "O")

    def w():
        o.x = o.x + 1

    ts = [threading.Thread(target=w, name=f"w{i}") for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_same_seed_same_schedule_and_exact_replay():
    r1 = sched.run_one(_racy_harness, seed=3)
    r2 = sched.run_one(_racy_harness, seed=3)
    assert r1.choices == r2.choices
    ops1 = [(t.task, t.op, t.obj) for t in r1.trace]
    ops2 = [(t.task, t.op, t.obj) for t in r2.trace]
    assert ops1 == ops2

    rep = sched.replay(_racy_harness, r1.choices, r1.seed)
    assert not rep.diverged
    assert [(t.task, t.op, t.obj) for t in rep.trace] == ops1
    assert rep.race_keys == r1.race_keys


def test_explore_finds_lost_update_race_and_minimizes():
    res = sched.explore(_racy_harness, "racy", schedules=16, seed=0)
    assert any("O.x" in r["var"] for r in res.races)
    for r in res.races:
        assert isinstance(r["seed"], int)  # printed repro seed
        assert r["minimal_schedule"], "evidence must carry a schedule"
        # the window ends at the racing access and stays printable
        assert len(r["minimal_schedule"]) <= 30


def test_locked_counter_is_race_free():
    def h():
        class Obj:
            pass

        o = Obj()
        o.x = 0
        lk = threading.Lock()
        sched.instrument(o, ["x"], "L")

        def w():
            with lk:
                o.x = o.x + 1

        ts = [threading.Thread(target=w) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert o.x == 2

    res = sched.explore(h, "locked", schedules=12, seed=0)
    assert res.races == [] and res.failure is None


def test_abba_deadlock_is_detected():
    def h():
        l1 = threading.Lock()
        l2 = threading.Lock()

        def a():
            with l1:
                with l2:
                    pass

        def b():
            with l2:
                with l1:
                    pass

        ts = [
            threading.Thread(target=a, name="a"),
            threading.Thread(target=b, name="b"),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    res = sched.explore(h, "abba", schedules=24, seed=0)
    assert res.failure is not None
    assert res.failure["kind"] == "deadlock"
    assert res.failure["minimal_schedule"]


# ---------------------------------------------------------------------------
# happens-before detector (pure unit — no shim)


def test_hb_channel_orders_and_unordered_races():
    st = hb.HBState()
    assert st.access("a", "v", True, "f:1") is None
    st.release("a", "ch")
    st.acquire("b", "ch")
    assert st.access("b", "v", True, "f:2") is None  # ordered via ch
    r = st.access("c", "v", True, "f:3")  # c never synchronized
    assert r is not None and r.var == "v"
    # dedup: the same site pair reports once
    assert st.access("c", "v", True, "f:3") is None


def test_hb_fork_join_edges():
    st = hb.HBState()
    st.access("parent", "v", True, "f:1")
    st.fork("parent", "child")
    assert st.access("child", "v", True, "f:2") is None  # after fork
    st.join("parent", "child")
    assert st.access("parent", "v", False, "f:3") is None  # after join


# ---------------------------------------------------------------------------
# mutation regression corpus (the three PR 7 fixed races)


@pytest.mark.parametrize(
    "name",
    ["mut-pusher-backoff", "mut-controller-updaters", "mut-conn-close"],
)
def test_mutation_corpus_reproduces_deterministically(name):
    h = H.HARNESSES[name]
    r1 = sched.explore(
        h.fn, name, schedules=h.schedules, seed=0, max_ops=h.max_ops
    )
    assert r1.evidence, f"{name} found no evidence"
    for key in h.expect_keys:
        assert H._evidence_matches(r1, key), f"{name}: no evidence for {key}"
    for race in r1.races:
        assert isinstance(race["seed"], int)
        assert race["minimal_schedule"]

    # fixed seed => identical rediscovery (repro seeds and race keys)
    r2 = sched.explore(
        h.fn, name, schedules=h.schedules, seed=0, max_ops=h.max_ops
    )
    assert [r["var"] for r in r1.races] == [r["var"] for r in r2.races]
    assert [r["seed"] for r in r1.races] == [r["seed"] for r in r2.races]


def test_guarded_counterparts_stay_clean():
    for name in ("pusher-backoff", "controller-updaters", "conn-close"):
        h = H.HARNESSES[name]
        res = sched.explore(h.fn, name, schedules=8, seed=0, max_ops=h.max_ops)
        assert not res.evidence, f"{name}: {res.races or res.failure}"


def test_verdicts_confirm_static_sites():
    results = {}
    for name in (
        "pusher-backoff", "mut-pusher-backoff",
        "controller-updaters", "mut-controller-updaters",
    ):
        h = H.HARNESSES[name]
        results[name] = sched.explore(
            h.fn, name, schedules=h.schedules, seed=0, max_ops=h.max_ops
        )
    vs = {v["site"]: v["verdict"] for v in H.verdicts(results)}
    assert (
        vs["edl_tpu/obs/fleet.py:MetricsPusher._fail_streak"] == "CONFIRMED"
    )
    assert (
        vs["edl_tpu/controller/controller.py:Controller.updaters"]
        == "CONFIRMED"
    )


# ---------------------------------------------------------------------------
# CLI verb


def test_cli_schedcheck_json_traces_and_exit_codes(tmp_path, capsys):
    rc = cli_main([
        "schedcheck", "pusher-backoff", "mut-pusher-backoff",
        "--json", "--trace-dir", str(tmp_path / "tr"),
    ])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0 and doc["ok"]
    assert {h["harness"] for h in doc["harnesses"]} == {
        "pusher-backoff", "mut-pusher-backoff"
    }
    verdicts = {v["site"]: v["verdict"] for v in doc["verdicts"]}
    assert (
        verdicts["edl_tpu/obs/fleet.py:MetricsPusher._fail_streak"]
        == "CONFIRMED"
    )
    assert (tmp_path / "tr" / "mut-pusher-backoff.jsonl").exists()
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "tr" / "mut-pusher-backoff.jsonl")
        .read_text()
        .splitlines()
    ]
    assert lines[0]["type"] == "summary"
    assert any(ln["type"] == "race" for ln in lines)

    rc = cli_main(["schedcheck", "--list"])
    capsys.readouterr()
    assert rc == 0

    rc = cli_main(["schedcheck", "no-such-harness"])
    capsys.readouterr()
    assert rc == 2


def test_cli_schedcheck_text_prints_minimal_schedule(capsys):
    rc = cli_main(["schedcheck", "mut-controller-updaters", "--seed", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "minimal schedule" in out
    assert "repro: seed" in out
    assert "Controller.updaters" in out
