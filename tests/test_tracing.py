"""Tracing subsystem + reshard span instrumentation + checkpoint/resume."""

import json
import os

import jax
import numpy as np
import optax
import pytest

from edl_tpu.models import linreg
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _clear_tracer():
    tracing.tracer().clear()
    yield
    tracing.tracer().clear()


def _data_fn(bs, seed=0):
    x, y = linreg.synthetic_dataset(max(bs, 64), seed=seed)
    return lambda n: {"x": x[:n], "y": y[:n]}


def _trainer(**kw):
    return ElasticTrainer(
        linreg.loss_fn, optax.sgd(0.05), chips_per_worker=1, per_chip_batch=8, **kw
    )


def test_span_recording_and_chrome_dump(tmp_path):
    tr = tracing.Tracer()
    with tr.span("outer", job="j"):
        with tr.span("inner"):
            pass
    assert [s.name for s in tr.spans()] == ["inner", "outer"]
    assert tr.spans("outer")[0].attrs == {"job": "j"}
    assert tr.summary()["outer"]["count"] == 1
    assert tr.summary()["_tracer"] == {"spans": 2, "dropped": 0}

    g_path = str(tmp_path / "t.json")
    tr.dump(g_path)
    with open(g_path) as f:
        doc = json.load(f)
    assert doc["dropped"] == 0
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(events) == 2
    assert all(e["dur"] >= 0 for e in events)
    # the ring-buffer accounting rides as chrome-trace metadata
    assert meta and meta[0]["args"]["dropped"] == 0
    # inner nests within outer on the timeline
    inner = next(e for e in events if e["name"] == "inner")
    outer = next(e for e in events if e["name"] == "outer")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0


def test_ring_buffer_keeps_most_recent_spans():
    """Overflow policy: the ring evicts the OLDEST span (the old
    behavior silently dropped the NEWEST — exactly the spans closest
    to an incident) and the eviction count surfaces everywhere."""
    tr = tracing.Tracer(max_spans=3)
    for i in range(7):
        tr.record(f"s{i}", 0.0, 0.1)
    assert [s.name for s in tr.spans()] == ["s4", "s5", "s6"]
    assert tr.dropped == 4
    assert tr.summary()["_tracer"] == {"spans": 3, "dropped": 4}
    doc = tr.to_chrome_doc()
    assert doc["dropped"] == 4
    meta = next(e for e in doc["traceEvents"] if e["ph"] == "M")
    assert meta["args"]["dropped"] == 4 and meta["args"]["max_spans"] == 3
    tr.clear()
    assert tr.dropped == 0 and tr.spans() == []


def test_tracer_listener_sees_every_span():
    tr = tracing.Tracer()
    seen = []
    listener = lambda s: seen.append(s.name)  # noqa: E731
    tr.add_listener(listener)
    with tr.span("a"):
        pass
    tr.record("b", 0.0, 0.5)
    assert seen == ["a", "b"]
    tr.remove_listener(listener)
    tr.record("c", 0.0, 0.5)
    assert seen == ["a", "b"]


def test_reshard_emits_spans(cpu_devices):
    t = _trainer(devices=cpu_devices[:4])
    t.start(linreg.init_params(jax.random.PRNGKey(0)), n_workers=2)
    data = _data_fn(64)
    t.train_steps(data, 2)
    t.request_rescale(4)
    t.train_steps(data, 2)
    names = {s.name for s in tracing.tracer().spans()}
    assert "reshard" in names
    assert "reshard.build_mesh" in names
    assert "reshard.recompile" in names
    ev = tracing.tracer().spans("reshard")[0]
    assert ev.attrs["from_workers"] == 2 and ev.attrs["to_workers"] == 4


def test_periodic_checkpoint_and_resume(tmp_path, cpu_devices):
    cdir = str(tmp_path / "ckpt")
    t = _trainer(
        devices=cpu_devices[:4], checkpoint_dir=cdir, checkpoint_every_steps=2
    )
    t.start(linreg.init_params(jax.random.PRNGKey(0)), n_workers=2)
    t.train_steps(_data_fn(64), 5)
    assert os.path.isdir(os.path.join(cdir, "step-2"))
    assert os.path.isdir(os.path.join(cdir, "step-4"))
    assert "checkpoint.save" in tracing.tracer().summary()

    # resume onto a DIFFERENT worker count (elastic warm restart)
    t2 = _trainer(devices=cpu_devices[:4])
    t2.resume(
        linreg.init_params(jax.random.PRNGKey(1)),
        n_workers=4,
        checkpoint_path=os.path.join(cdir, "step-4"),
    )
    assert int(np.asarray(jax.device_get(t2.state.step))) == 4
    assert ckpt.load_metadata(os.path.join(cdir, "step-4"))["n_workers"] == 2

    # resumed params equal the checkpointed ones, not the fresh template
    from edl_tpu.train.trainer import TrainState

    saved = ckpt.load(
        os.path.join(cdir, "step-4"),
        TrainState.create(linreg.init_params(jax.random.PRNGKey(1)), optax.sgd(0.05)),
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(t2.state.params["w"])),
        np.asarray(saved.params["w"]),
    )
    report = t2.train_steps(_data_fn(64), 2)
    assert int(np.asarray(jax.device_get(t2.state.step))) == 6
    assert np.isfinite(report.losses).all()


def test_force_checkpoint(tmp_path, cpu_devices):
    t = _trainer(devices=cpu_devices[:2], checkpoint_dir=str(tmp_path))
    t.start(linreg.init_params(jax.random.PRNGKey(0)), n_workers=2)
    t.train_steps(_data_fn(32), 1)
    path = t.maybe_checkpoint(force=True)
    assert path and os.path.isdir(path)
    assert t.maybe_checkpoint(force=True) is None  # same step: no rewrite
