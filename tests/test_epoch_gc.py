"""Deterministic tests of the epoch-scoped KV GC ledger (VERDICT r4
#4): the two-lane deferral protocol of runtime/epoch_gc.py, driven
through simulated epoch sequences with NO subprocesses and NO timing —
the invariant ("no key is deleted while a reader can still need it,
none leaks") previously rode only on multiproc luck."""

import pytest

from edl_tpu.runtime.epoch_gc import EpochKeyGC


class FakeKV:
    """Dict-backed KV recording every delete, so a test can assert
    exactly WHEN a key died relative to the protocol sequence."""

    def __init__(self):
        self.data = {}
        self.deletes = []

    def put(self, k, v="1"):
        self.data[k] = v

    def delete(self, k):
        self.deletes.append(k)
        self.data.pop(k, None)


def test_defer_deleted_at_next_drain():
    gc, kv = EpochKeyGC(), FakeKV()
    kv.put("go/1")
    gc.defer("go/1")
    assert "go/1" in kv.data  # still live until the drain point
    gc.drain(kv.delete)
    assert "go/1" not in kv.data
    assert gc.pending() == 0


def test_defer_late_survives_exactly_one_drain():
    """The round-4 foot-gun, as a law: a key written DURING an epoch
    that same-epoch peers still poll after this worker's drain point
    must survive THAT drain and die at the next one."""
    gc, kv = EpochKeyGC(), FakeKV()
    kv.put("restore/5")
    gc.defer_late("restore/5")
    gc.drain(kv.delete)  # the same epoch's own drain
    assert "restore/5" in kv.data, "deleted while peers still poll it"
    gc.drain(kv.delete)  # next epoch's drain: readers are gone
    assert "restore/5" not in kv.data
    assert gc.pending() == 0


def test_worker_epoch_sequence_no_early_delete_no_leak():
    """The full protocol shape across three epochs: restore decision +
    restore marks (late lane, written mid-epoch), teardown's go/dist/
    disc (normal lane, written at epoch exit), dist_done (late lane).
    At every drain: nothing a same-epoch reader may still poll has
    died; after two more epochs every key of a finished epoch is gone."""
    gc, kv = EpochKeyGC(), FakeKV()

    def run_epoch(e):
        # -- rendezvous + restore phase (before this epoch's drain)
        kv.put(f"restore/{e}")
        gc.defer_late(f"restore/{e}")
        # -- drain point (just after jax.distributed connect)
        gc.drain(kv.delete)
        # INVARIANT: this epoch's restore key must survive its own
        # epoch's drain — peers are still polling it right now
        assert f"restore/{e}" in kv.data
        # -- restore marks written after the drain, same epoch
        kv.put(f"restored/{e}/w0")
        gc.defer_late(f"restored/{e}/w0")
        # -- teardown at epoch exit
        for k in (f"go/{e}", f"dist/{e}", f"disc/{e}/w0", f"disc/{e}/w1"):
            kv.put(k)
            gc.defer(k)
        kv.put(f"dist_done/{e}")
        gc.defer_late(f"dist_done/{e}")

    for e in range(3):
        run_epoch(e)
        if e >= 1:
            prev = e - 1
            # teardown keys of the PREVIOUS epoch died at this epoch's
            # drain (nobody reads them once everyone connected here)...
            assert f"go/{prev}" not in kv.data
            assert f"disc/{prev}/w0" not in kv.data
        if e >= 2:
            # ...and the previous-previous epoch's late-lane keys are
            # gone too: nothing leaks beyond two epochs (epoch is the
            # second path segment of every key here)
            pp = e - 2
            assert not any(
                k.split("/")[1] == str(pp) for k in kv.data
            ), kv.data
    # two final drains flush everything owed
    gc.drain(kv.delete)
    gc.drain(kv.delete)
    assert gc.pending() == 0
    assert kv.data == {}, f"leaked: {kv.data}"


def test_regroup_after_failed_restore_defers_again_without_leak():
    """A failed restore regroups WITHOUT reaching the drain point
    (worker_main bumps its incarnation and re-rendezvouses): the failed
    epoch's decision key stays deferred and dies on the eventual
    successful epoch's schedule, exactly once."""
    gc, kv = EpochKeyGC(), FakeKV()
    # epoch 7: decision published, assembly fails before the drain
    kv.put("restore/7")
    gc.defer_late("restore/7")
    # epoch 8 (regroup): new decision, reaches its drain
    kv.put("restore/8")
    gc.defer_late("restore/8")
    gc.drain(kv.delete)
    assert "restore/7" in kv.data and "restore/8" in kv.data
    gc.drain(kv.delete)
    assert "restore/7" not in kv.data and "restore/8" not in kv.data
    assert kv.deletes.count("restore/7") == 1


def test_dead_service_host_sweep_is_late():
    """A failed distributed init retracts the endpoint and marks the
    host dismissed; the mark is swept one epoch LATE so the worker
    cannot win a race against a live host's own dismissal poll."""
    gc, kv = EpochKeyGC(), FakeKV()
    kv.put("dist_done/3/9001")
    gc.defer_late("dist_done/3/9001")
    gc.drain(kv.delete)  # the retry epoch's drain
    assert "dist_done/3/9001" in kv.data  # host may still be polling
    gc.drain(kv.delete)
    assert "dist_done/3/9001" not in kv.data


def test_drain_failure_keeps_remaining_keys_owed():
    """A transient coordinator hiccup mid-drain must not leak the rest
    forever: undeleted keys stay owed and the next drain retries them;
    late keys keep their extra-epoch guarantee (promotion only happens
    after the due list fully drains)."""
    gc, kv = EpochKeyGC(), FakeKV()
    for k in ("a", "b", "c"):
        kv.put(k)
        gc.defer(k)
    kv.put("late1")
    gc.defer_late("late1")

    calls = []

    def flaky_delete(k):
        calls.append(k)
        if len(calls) == 2:
            raise ConnectionError("coordinator hiccup")
        kv.delete(k)

    with pytest.raises(ConnectionError):
        gc.drain(flaky_delete)
    assert "a" not in kv.data  # first delete landed
    assert gc.pending() == 3  # b, c still owed + late1 not promoted
    gc.drain(kv.delete)  # retry: b, c die, late1 promotes
    assert "b" not in kv.data and "c" not in kv.data
    assert "late1" in kv.data
    gc.drain(kv.delete)
    assert kv.data == {}


def test_extend_bulk_api():
    gc, kv = EpochKeyGC(), FakeKV()
    gc.extend(["x", "y"])
    gc.extend(["z"], late=True)
    assert gc.due == ("x", "y") and gc.late == ("z",)
    gc.drain(kv.delete)
    assert kv.deletes == ["x", "y"]
