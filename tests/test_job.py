"""TrainingJob spec, quantities, defaulting, validation.

Ports the reference predicate/unit tests
(pkg/resource/training_job_test.go:27-46, pkg/utils_test.go:25-48,
pkg/autoscaler_internal_test.go:96-101) onto the TPU resource model.
"""

import pytest

from edl_tpu.api.job import JobPhase, TrainingJob
from edl_tpu.api.parser import JobParser, ValidationError
from edl_tpu.api.resources import (
    ResourceSpec,
    add_resource_list,
    cpu_milli,
    mem_mega,
    parse_quantity,
)

EXAMPLE_YAML = """
apiVersion: edl-tpu.org/v1
kind: TrainingJob
metadata:
  name: example
spec:
  image: "edl-tpu/example"
  port: 7164
  fault_tolerant: true
  accelerator_type: v5e
  worker:
    entrypoint: "python /workspace/train_ft.py"
    workspace: "/workspace"
    passes: 50
    min_replicas: 2
    max_replicas: 10
    resources:
      requests: {cpu: "200m", memory: "200Mi", tpu: 4}
      limits: {cpu: "200m", memory: "200Mi", tpu: 4}
  pserver:
    min_replicas: 2
    max_replicas: 2
  master:
    resources:
      requests: {cpu: "500m", memory: "600Mi"}
      limits: {cpu: "1", memory: "1Gi"}
"""

# The reference's legacy YAML keys (min-instance, trainer:) must also parse.
LEGACY_YAML = """
metadata: {name: legacy}
spec:
  fault_tolerant: true
  trainer:
    entrypoint: "python train.py"
    min-instance: 2
    max-instance: 6
    resources:
      requests: {cpu: "1", memory: "1Gi"}
"""


def test_quantity_parsing():
    # reference: TestTrainerRequestLimit autoscaler_internal_test.go:96-101
    assert cpu_milli("1k") == 1_000_000
    assert mem_mega("100Mi") == 105
    assert parse_quantity("10") == 10
    assert cpu_milli("200m") == 200
    assert cpu_milli("1") == 1000
    assert mem_mega("1Gi") == 1074


def test_resource_list_accumulate():
    # reference: pkg/utils_test.go:25-48
    dst = {"cpu": 1000.0, "memory": 100.0}
    add_resource_list(dst, {"cpu": 500.0, "tpu": 4.0})
    assert dst == {"cpu": 1500.0, "memory": 100.0, "tpu": 4.0}
    a = ResourceSpec(1000, 100, 4) + ResourceSpec(200, 50, 4)
    assert (a.cpu_milli, a.mem_mega, a.tpu_chips) == (1200, 150, 8)
    assert ResourceSpec(100, 10, 1).scaled(3).tpu_chips == 3


def test_from_yaml_and_predicates():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    assert job.name == "example"
    assert job.spec.worker.min_replicas == 2
    assert job.spec.worker.max_replicas == 10
    assert job.elastic()  # reference: training_job_test.go Elastic
    assert job.need_tpu()  # reference: training_job_test.go NeedGPU
    assert job.chips_per_worker() == 4
    assert job.spec.worker.resources.requests.cpu_milli == 200
    assert job.spec.master.resources.limits.mem_mega == 1074
    assert job.status.phase == JobPhase.NONE


def test_legacy_yaml_keys():
    job = TrainingJob.from_yaml(LEGACY_YAML)
    assert job.spec.worker.min_replicas == 2
    assert job.spec.worker.max_replicas == 6
    assert job.elastic()
    assert not job.need_tpu()


def test_validate_defaults():
    # reference: Validate defaulting pkg/jobparser.go:47-65
    job = TrainingJob.from_yaml(LEGACY_YAML)
    warnings = JobParser().validate(job)
    assert job.spec.port == 7164
    assert job.spec.passes == 1
    assert job.spec.image != ""
    assert job.spec.accelerator_type == "v5e"
    assert warnings == []


def test_validate_elastic_requires_fault_tolerant():
    # reference: pkg/jobparser.go:66-68
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    job.spec.fault_tolerant = False
    with pytest.raises(ValidationError):
        JobParser().validate(job)


def test_validate_rejects_non_pow2_chips():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    job.spec.worker.resources.limits.tpu_chips = 3
    job.spec.worker.resources.requests.tpu_chips = 3
    with pytest.raises(ValidationError):
        JobParser().validate(job)


def test_validate_warns_on_pserver():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    warnings = JobParser().validate(job)
    assert any("pserver" in w for w in warnings)


def test_parse_plans():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    p = JobParser()
    p.validate(job)
    coord = p.parse_to_coordinator(job)
    workers = p.parse_to_workers(job)
    # reference: ParseToTrainer Parallelism=min (jobparser.go:120-128)
    assert workers.parallelism == 2
    assert workers.chips_per_worker == 4
    assert workers.restart_policy == "Never"  # reference: jobparser.go:160
    assert coord.name == "example-coordinator"
    env = workers.env
    assert env["EDL_JOB_NAME"] == "example"
    assert env["EDL_WORKERS_MAX"] == "10"
    assert env["EDL_FAULT_TOLERANT"] == "1"
    assert "example-coordinator" in env["EDL_COORDINATOR"]


def test_spec_env_passthrough():
    """spec.env carries the runtime's EDL_* knobs (EDL_MODEL,
    EDL_INT8_MXU, ...) into the worker env; derived contract keys
    always win and the collision warns; both YAML shapes parse and
    values stringify; to_dict round-trips."""
    job = TrainingJob.from_dict({
        "metadata": {"name": "envjob"},
        "spec": {
            "fault_tolerant": True,
            "env": {
                "EDL_MODEL": "llama",
                "EDL_INT8_MXU": 1,       # YAML int -> "1"
                "EDL_WORKERS_MIN": "99",  # reserved: must be shadowed
            },
            "worker": {"min_replicas": 2, "max_replicas": 4},
        },
    })
    p = JobParser()
    warnings = p.validate(job)
    assert any("EDL_WORKERS_MIN" in w for w in warnings)
    env = p.parse_to_workers(job).env
    assert env["EDL_MODEL"] == "llama"
    assert env["EDL_INT8_MXU"] == "1"
    assert env["EDL_WORKERS_MIN"] == "2"  # the derived contract won

    # k8s container-style list form
    j2 = TrainingJob.from_dict({
        "metadata": {"name": "e2"},
        "spec": {
            "env": [{"name": "EDL_SYNC_EVERY", "value": "4"}],
            "worker": {"min_replicas": 1},
        },
    })
    assert j2.spec.env == {"EDL_SYNC_EVERY": "4"}
    assert TrainingJob.from_dict(j2.to_dict()).spec.env == j2.spec.env

    # malformed shapes are hard errors, not silent drops
    with pytest.raises(ValueError):
        TrainingJob.from_dict(
            {"metadata": {"name": "b"}, "spec": {"env": [{"value": "x"}]}}
        )
    with pytest.raises(ValueError):
        TrainingJob.from_dict(
            {"metadata": {"name": "b"}, "spec": {"env": "EDL_MODEL=llama"}}
        )


def test_spec_env_bool_and_valuefrom_handling():
    """YAML booleans normalize to the contract's "1"/"0" (str(False)
    would silently misread as enabled downstream); k8s valueFrom
    entries are hard errors, not silent empty strings."""
    j = TrainingJob.from_dict({
        "metadata": {"name": "b"},
        "spec": {
            "env": {"EDL_P2P": False, "EDL_INT8_MXU": True},
            "worker": {"min_replicas": 1},
        },
    })
    assert j.spec.env == {"EDL_P2P": "0", "EDL_INT8_MXU": "1"}
    j2 = TrainingJob.from_dict({
        "metadata": {"name": "b2"},
        "spec": {
            "env": [{"name": "EDL_INT8_MXU", "value": True}],
            "worker": {"min_replicas": 1},
        },
    })
    assert j2.spec.env == {"EDL_INT8_MXU": "1"}
    with pytest.raises(ValueError):
        TrainingJob.from_dict({
            "metadata": {"name": "b3"},
            "spec": {
                "env": [{
                    "name": "EDL_MODEL",
                    "valueFrom": {"configMapKeyRef": {"name": "cm"}},
                }],
            },
        })
