"""Benchmark — CTR elastic-DP throughput + reshard stall on real hardware.

The BASELINE metric (BASELINE.json): examples/sec/chip on the CTR
workload plus rescale-stall seconds. On the single bench chip we
measure per-chip training throughput of the Criteo-shaped CTR model
(the reference's production workload, example/ctr/ctr/train.py) and the
single-chip component of a reshard (device→host snapshot + host→device
re-placement of the full train state — the traffic-stopping window of
the elastic protocol).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline is 1.0: the reference publishes no throughput numbers
(BASELINE.json "published": {}), so this bench line is the baseline
being established for later rounds.
"""

import getpass
import json
import os
import tempfile
import time

import jax

# persistent compilation cache: the sorted-blockmatmul embedding
# backward is expensive to compile (~1-2 min); repeated bench runs on
# the same machine hit the cache and skip it. Per-user path: a fixed
# /tmp name breaks (and is poisonable) on shared hosts.
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
    tempfile.gettempdir(), f"edl_jax_cache_{getpass.getuser()}"
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models import ctr
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.train.trainer import (
    TrainState,
    make_train_multistep,
    shard_state,
    stack_batches,
)

BATCH = 16384
WARMUP = 2  # chunks (CHUNK steps each) before timing
MEASURE = 30
CHUNK = 6  # steps fused per dispatch (lax.scan) in the measure loop


def main() -> None:
    n_dev = len(jax.devices())
    plan = MeshPlan.data_parallel(n_dev)
    mesh = plan.build()

    params = ctr.init_params(jax.random.PRNGKey(0))  # full-size: 2^20 vocab
    tx = optax.adam(1e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh)

    rng = np.random.RandomState(0)
    raw = [ctr.synthetic_batch(rng, BATCH) for _ in range(4)]
    # steps-fused chunk: one dispatch per CHUNK steps (the per-dispatch
    # overhead on a host-driven chip is ~1 ms); the whole bench drives
    # this one program, so only one expensive XLA compile is paid
    stacked = stack_batches(
        [raw[i % len(raw)] for i in range(CHUNK)], plan, mesh
    )
    multi = make_train_multistep(ctr.make_loss_fn(jnp.bfloat16), tx, plan, mesh)

    # NOTE: on tunneled backends block_until_ready can return before the
    # device work completes; a scalar value fetch is the reliable fence.
    t_compile = time.perf_counter()
    state, m = multi(state, stacked)
    float(m["loss"])  # fence: compile + first chunk
    compile_s = time.perf_counter() - t_compile
    for _ in range(WARMUP):
        state, m = multi(state, stacked)
    float(m["loss"])

    # fence ONCE per measure loop (chunks stay pipelined, as in a real
    # training loop — a fence per chunk would serialize a host RTT into
    # every chunk), and take the best of two loops to suppress tunnel
    # jitter
    best_dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(MEASURE // CHUNK):
            state, m = multi(state, stacked)
        float(m["loss"])  # scalar fetch fences the dependent chain
        best_dt = min(best_dt, time.perf_counter() - t0)
    eps_per_chip = BATCH * (MEASURE // CHUNK) * CHUNK / best_dt / n_dev

    # flagship (Llama + pallas flash attention) train-step throughput:
    # the d512/L4 graft-entry config, bf16, T=2048 causal
    from edl_tpu.models import llama

    lcfg = llama.LlamaConfig(
        vocab=32768,
        d_model=512,
        n_layers=4,
        n_heads=8,
        n_kv_heads=4,
        d_ff=1536,
        dtype=jnp.bfloat16,
        # interpret-mode pallas off-TPU would take hours; XLA attention
        # keeps the bench smoke-runnable on a dev box
        use_flash=jax.devices()[0].platform == "tpu",
    )
    lb, lt = 8 * n_dev, 2048  # 8 sequences per chip on any mesh size
    lsteps = 2  # fused steps per dispatch
    lreps = 4  # dispatches per timed loop
    lstate = shard_state(
        TrainState.create(llama.init_params(jax.random.PRNGKey(1), lcfg), tx),
        plan,
        mesh,
    )
    ltoks = stack_batches(
        [llama.synthetic_tokens(rng, lb, lt, lcfg.vocab) for _ in range(lsteps)],
        plan,
        mesh,
    )
    lmulti = make_train_multistep(llama.make_loss_fn(lcfg), tx, plan, mesh)
    lstate, lm = lmulti(lstate, ltoks)
    float(lm["loss"])  # compile + warmup
    ltok_rate = 0.0
    for _ in range(2):
        t3 = time.perf_counter()
        for _ in range(lreps):
            lstate, lm = lmulti(lstate, ltoks)
        float(lm["loss"])
        ltok_rate = max(
            ltok_rate,
            lreps * lsteps * lb * lt / (time.perf_counter() - t3) / n_dev,
        )
    del lstate, ltoks

    # reshard stall, both protocol paths on this chip, min of 2 runs
    # (host<->device bandwidth on a tunneled chip is noisy; min is the
    # standard interference-suppressing estimator):
    # fast path — direct device-to-device re-placement (what an elastic
    # rescale uses when device sets overlap; rides ICI on multi-chip)
    from edl_tpu.runtime.elastic import _device_reshard

    stall_fast_s = stall_host_s = float("inf")
    state2 = state
    for _ in range(2):
        t1 = time.perf_counter()
        state2 = _device_reshard(state2, plan, mesh, None)
        float(jnp.sum(state2.params["out"]["b"]))
        stall_fast_s = min(stall_fast_s, time.perf_counter() - t1)
    # fallback path — host-RAM staging (worst case: disjoint devices),
    # down/up overlapped in one pipeline
    state3 = state2
    for _ in range(2):
        t2 = time.perf_counter()
        state3 = ckpt.staged_reshard(state3, plan, mesh)
        float(jnp.sum(state3.params["out"]["b"]))
        stall_host_s = min(stall_host_s, time.perf_counter() - t2)

    print(
        json.dumps(
            {
                "metric": "ctr_examples_per_sec_per_chip",
                "value": round(eps_per_chip, 1),
                "unit": "examples/s/chip",
                "vs_baseline": 1.0,
                "reshard_stall_s": round(stall_fast_s, 4),
                "reshard_stall_host_fallback_s": round(stall_host_s, 4),
                "llama_tokens_per_sec_per_chip": round(ltok_rate, 1),
                "compile_s": round(compile_s, 2),
                "final_loss": round(float(m["loss"]), 4),
                "n_devices": n_dev,
                "platform": jax.devices()[0].platform,
                "global_batch": BATCH,
            }
        )
    )


if __name__ == "__main__":
    main()
