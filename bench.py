"""Benchmark — CTR elastic-DP throughput + reshard stall on real hardware.

The BASELINE metric (BASELINE.json): examples/sec/chip on the CTR
workload plus rescale-stall seconds. On the single bench chip we
measure per-chip training throughput of the Criteo-shaped CTR model
(the reference's production workload, example/ctr/ctr/train.py) and the
single-chip component of a reshard (device→host snapshot + host→device
re-placement of the full train state — the traffic-stopping window of
the elastic protocol).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline is 1.0: the reference publishes no throughput numbers
(BASELINE.json "published": {}), so this bench line is the baseline
being established for later rounds.
"""

import getpass
import json
import os
import tempfile
import time

import jax

# persistent compilation cache: the sorted-blockmatmul embedding
# backward is expensive to compile (~1-2 min); repeated bench runs on
# the same machine hit the cache and skip it. Per-user path: a fixed
# /tmp name breaks (and is poisonable) on shared hosts.
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
    tempfile.gettempdir(), f"edl_jax_cache_{getpass.getuser()}"
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models import ctr
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.train.trainer import TrainState, global_batch, make_train_step, shard_state

BATCH = 16384
WARMUP = 5
MEASURE = 30


def main() -> None:
    n_dev = len(jax.devices())
    plan = MeshPlan.data_parallel(n_dev)
    mesh = plan.build()

    params = ctr.init_params(jax.random.PRNGKey(0))  # full-size: 2^20 vocab
    tx = optax.adam(1e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh)
    step = make_train_step(ctr.make_loss_fn(jnp.bfloat16), tx, plan, mesh)

    rng = np.random.RandomState(0)
    batches = [
        global_batch(ctr.synthetic_batch(rng, BATCH), plan, mesh) for _ in range(4)
    ]

    # NOTE: on tunneled backends block_until_ready can return before the
    # device work completes; a scalar value fetch is the reliable fence.
    t_compile = time.perf_counter()
    state, m = step(state, batches[0])
    float(m["loss"])  # fence: compile + first step only
    compile_s = time.perf_counter() - t_compile
    for i in range(1, WARMUP):
        state, m = step(state, batches[i % len(batches)])
    float(m["loss"])

    t0 = time.perf_counter()
    for i in range(MEASURE):
        state, m = step(state, batches[i % len(batches)])
    float(m["loss"])  # scalar fetch fences the whole dependent chain
    dt = time.perf_counter() - t0
    eps_per_chip = BATCH * MEASURE / dt / n_dev

    # reshard stall, both protocol paths on this chip:
    # fast path — direct device-to-device re-placement (what an elastic
    # rescale uses when device sets overlap; rides ICI on multi-chip)
    from edl_tpu.runtime.elastic import _device_reshard

    t1 = time.perf_counter()
    state2 = _device_reshard(state, plan, mesh, None)
    float(jnp.sum(state2.params["out"]["b"]))
    stall_fast_s = time.perf_counter() - t1
    # fallback path — full host-RAM staging (worst case: disjoint devices)
    t2 = time.perf_counter()
    host = ckpt.snapshot(state2)
    state3 = ckpt.restore(host, plan, mesh)
    float(jnp.sum(state3.params["out"]["b"]))
    stall_host_s = time.perf_counter() - t2

    print(
        json.dumps(
            {
                "metric": "ctr_examples_per_sec_per_chip",
                "value": round(eps_per_chip, 1),
                "unit": "examples/s/chip",
                "vs_baseline": 1.0,
                "reshard_stall_s": round(stall_fast_s, 4),
                "reshard_stall_host_fallback_s": round(stall_host_s, 4),
                "compile_s": round(compile_s, 2),
                "final_loss": round(float(m["loss"]), 4),
                "n_devices": n_dev,
                "platform": jax.devices()[0].platform,
                "global_batch": BATCH,
            }
        )
    )


if __name__ == "__main__":
    main()
