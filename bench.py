"""Benchmark — CTR elastic-DP throughput + reshard stall on real hardware.

The BASELINE metric (BASELINE.json): examples/sec/chip on the CTR
workload plus rescale-stall seconds. On the single bench chip we
measure per-chip training throughput of the Criteo-shaped CTR model
(the reference's production workload, example/ctr/ctr/train.py) and the
single-chip component of a reshard (device→host snapshot + host→device
re-placement of the full train state — the traffic-stopping window of
the elastic protocol).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline is 1.0: the reference publishes no throughput numbers
(BASELINE.json "published": {}), so this bench line is the baseline
being established for later rounds.
"""

import json
import time

import jax

# persistent compilation cache: the sorted-blockmatmul embedding
# backward is expensive to compile (~1-2 min); repeated bench runs on
# the same machine hit the cache and skip it (shared policy:
# edl_tpu/utils/jaxcache.py)
from edl_tpu.utils import jaxcache

jaxcache.configure()
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models import ctr
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.train.trainer import (
    TrainState,
    make_train_multistep,
    shard_state,
    stack_batches,
)

BATCH = 16384
WARMUP = 2  # chunks (CHUNK steps each) before timing
# Measurement methodology (revised r3): the tunnel's dependent-scalar
# fence costs ~70 ms of host RTT PER MEASURE LOOP, so short loops
# under-report steady-state throughput by >10% (the r01->r02 "CTR
# regression" was this dilution plus cross-session tunnel drift —
# same-session A/B of the two code states agrees within 0.3%, see
# scripts/ctr_probe.py). Long loops (240 steps) dilute the fence to
# <3%; CHUNK=12 halves dispatch overhead vs 6 (measured +5%), while
# 30-step scans regress (unroll/memory pressure).
MEASURE = 240
CHUNK = 12  # steps fused per dispatch (lax.scan) in the measure loop

# device peaks (MFU / roofline denominators) live in the shared cost
# model (edl_tpu/obs/costmodel.py) — the ONE table bench, exp_mfu, and
# the live efficiency gauges read. Spec values, no env overrides here:
# published pct-of-peak must stay comparable across rounds.
from edl_tpu.obs import costmodel as _costmodel


def _peak_flops(device) -> float:
    return _costmodel.peak_for_device(device).flops


def flagship_train_config():
    """THE flagship model definition (BASELINE config #5 at the scale
    one v5e chip trains): d2048/L16/ff6144/v32768, bf16 activations,
    pallas flash attention, per-layer remat. The ONE factory bench and
    every scripts/exp_* measurement import — four inline copies of
    this literal had already appeared, and a drifted copy silently
    invalidates "same config as the published numbers" claims."""
    import jax.numpy as jnp

    from edl_tpu.models import llama

    return llama.LlamaConfig(
        vocab=32768, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=8, d_ff=6144, dtype=jnp.bfloat16, use_flash=True,
        remat=True,
    )


def flagship_decode_config():
    """The serving twin: same architecture, no remat (inference holds
    no activations worth trading FLOPs for)."""
    import dataclasses

    return dataclasses.replace(flagship_train_config(), remat=False)


def _llama_measure(lcfg, lt, ladder, lsteps, lreps, n_dev, plan, mesh, rng):
    """Train-throughput ladder for one llama config: walk per-chip batch
    sizes down until one fits, return (tokens/s/chip, used_batch,
    state_gb). OOM (or any per-rung failure: a too-big program can also
    kill the remote compile helper) steps down; only the LAST rung's
    failure propagates."""
    import optax

    from edl_tpu.models import llama

    ltx = optax.adafactor(1e-3)
    pspecs = llama.param_pspecs(lcfg, plan)
    for per_chip in ladder:
        lb = per_chip * n_dev
        ltok_rate = 0.0  # a partially-timed bigger rung must not leak in
        lstate = ltoks = None
        try:
            lstate = jax.jit(
                lambda: TrainState.create(
                    llama.init_params(jax.random.PRNGKey(1), lcfg), ltx
                )
            )()
            lstate = shard_state(lstate, plan, mesh, pspecs)
            ltoks = stack_batches(
                [
                    llama.synthetic_tokens(rng, lb, lt, lcfg.vocab)
                    for _ in range(lsteps)
                ],
                plan,
                mesh,
            )
            lmulti = make_train_multistep(
                llama.make_loss_fn(lcfg), ltx, plan, mesh, pspecs
            )
            lstate, lm = lmulti(lstate, ltoks)
            float(lm["loss"])  # compile + warmup fence
            # best-of-3: the T=8192 rung's rate noise straddles the
            # long_mfu 0.50 bar (0.4999 vs 0.5007 across runs)
            for _ in range(3):
                t3 = time.perf_counter()
                for _ in range(lreps):
                    lstate, lm = lmulti(lstate, ltoks)
                float(lm["loss"])
                ltok_rate = max(
                    ltok_rate,
                    lreps * lsteps * lb * lt / (time.perf_counter() - t3) / n_dev,
                )
            state_gb = ckpt.state_nbytes(lstate) / (1 << 30)
            del lstate, ltoks
            jax.clear_caches()
            return ltok_rate, per_chip, state_gb
        except Exception as e:
            if per_chip == ladder[-1]:
                raise
            print(
                f"# llama bench: batch {per_chip}/chip failed "
                f"({str(e)[:120]}), stepping down"
            )
            del lstate, ltoks  # free the failed rung's HBM first
            jax.clear_caches()
    return 0.0, 0, 0.0  # pragma: no cover - ladder always returns/raises


def _llama_flagship_bench(n_dev, plan, mesh, rng) -> dict:
    """Flagship train throughput + MFU, plus a LONG-CONTEXT rung.
    On TPU the flagship is d2048/L16/ff6144, vocab 32k, T=2048, bf16
    activations, pallas flash attention, per-layer remat, adafactor
    (factored moments — Adam's 8 GB of f32 moments don't fit beside
    3.8 GB of f32 params in 16 GB HBM); the long-context rung trains
    the SAME architecture at T=8192 (16x the attention work per token,
    where causal block skipping and the flash kernel earn their keep).
    Off-TPU: tiny configs keep the script smoke-runnable."""
    from edl_tpu.models import llama

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        lcfg = flagship_train_config()
        lt, ladder = 2048, (16, 8, 4, 2)
        long_t, long_ladder = 8192, (4, 2, 1)
        lsteps, lreps = 2, 4  # fused steps/dispatch, dispatches/loop
    else:  # smoke config: exercise the same code path cheaply
        lcfg = llama.LlamaConfig(
            vocab=1024,
            d_model=128,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            d_ff=384,
            dtype=jnp.float32,
            remat=True,
        )
        lt, ladder = 256, (2,)
        long_t, long_ladder = 512, (1,)
        lsteps, lreps = 2, 2

    ltok_rate, used_batch, state_gb = _llama_measure(
        lcfg, lt, ladder, lsteps, lreps, n_dev, plan, mesh, rng
    )
    long_rate, long_batch, _ = _llama_measure(
        lcfg, long_t, long_ladder, lsteps, max(lreps // 2, 1),
        n_dev, plan, mesh, rng,
    )
    # int8 MXU training (VERDICT r4 #8): same config, the seven
    # projection matmuls on the double-rate int8 path
    # (ops/int8_matmul.py). Published beside the bf16 headline — `mfu`
    # stays bf16 for cross-round comparability; `int8_mfu` is
    # model-FLOPs over the *bf16* peak (an effective-MFU: >bf16-mfu
    # means the int8 path beat what bf16 could ever reach).
    import dataclasses as _dc

    int8_rate, int8_batch, _ = _llama_measure(
        _dc.replace(lcfg, int8_mxu=True), lt, ladder, lsteps, lreps,
        n_dev, plan, mesh, rng,
    )
    int8_long_rate, int8_long_batch, _ = _llama_measure(
        _dc.replace(lcfg, int8_mxu=True), long_t, long_ladder, lsteps,
        max(lreps // 2, 1), n_dev, plan, mesh, rng,
    )

    peak = _peak_flops(jax.devices()[0])
    fpt = llama.train_flops_per_token(lcfg, lt)
    long_fpt = llama.train_flops_per_token(lcfg, long_t)
    return {
        "llama_tokens_per_sec_per_chip": round(ltok_rate, 1),
        "mfu": round(ltok_rate * fpt / peak, 4) if on_tpu else 0.0,
        "llama_int8_tokens_per_sec_per_chip": round(int8_rate, 1),
        "int8_mfu": round(int8_rate * fpt / peak, 4) if on_tpu else 0.0,
        "llama_int8_batch": int8_batch,
        # a speedup is only a quantization effect if both runs settled
        # on the SAME ladder rung (the int8 run holds extra in-flight
        # quantized operands and could step down where bf16 didn't) —
        # a rung mismatch publishes the explicit sentinel instead
        "int8_train_speedup": (
            round(int8_rate / ltok_rate, 3)
            if ltok_rate > 0 and int8_batch == used_batch
            else -1.0
        ),
        "llama_config": (
            f"d{lcfg.d_model}/L{lcfg.n_layers}/ff{lcfg.d_ff}/"
            f"v{lcfg.vocab}/T{lt}/b{used_batch}"
        ),
        "llama_flops_per_token": round(fpt / 1e6, 1),  # MFLOPs
        "llama_long_tokens_per_sec_per_chip": round(long_rate, 1),
        "long_mfu": round(long_rate * long_fpt / peak, 4) if on_tpu else 0.0,
        "llama_long_config": f"T{long_t}/b{long_batch}",
        "llama_int8_long_tokens_per_sec_per_chip": round(int8_long_rate, 1),
        "int8_long_mfu": (
            round(int8_long_rate * long_fpt / peak, 4) if on_tpu else 0.0
        ),
        "llama_int8_long_batch": int8_long_batch,
        "int8_long_speedup": (
            round(int8_long_rate / long_rate, 3)
            if long_rate > 0 and int8_long_batch == long_batch
            else -1.0
        ),
        "peak_tflops": round(peak / 1e12, 1),
        "flagship_state_gb": round(state_gb, 2),
    }


_P2P_SERVER_SRC = """
import sys, time
import numpy as np
from edl_tpu.runtime.checkpoint import LocalSnapshot
from edl_tpu.runtime.shard_server import ShardServer

seed, n_pieces, rows = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
piece = np.random.RandomState(seed).rand(rows, 1024).astype(np.float32)
pieces = {"p:w": [((i * rows, 0), piece) for i in range(n_pieces)]}
snap = LocalSnapshot(
    step=1, pieces=pieces,
    primary={"p:w": [o for o, _ in pieces["p:w"]]},
    shapes={"p:w": (n_pieces * rows, 1024)}, dtypes={"p:w": "float32"},
)
srv = ShardServer(lambda: snap)
print(srv.port, flush=True)
time.sleep(120)
"""

_P2P_FETCHER_SRC = """
import sys, time
from edl_tpu.runtime.shard_server import RemotePieces, fetch_index

ports = [int(p) for p in sys.argv[1].split(",")]
reps = int(sys.argv[2])
best = float("inf")
for _ in range(reps):
    t0 = time.perf_counter()
    total = 0
    for port in ports:
        _, entries = fetch_index(f"127.0.0.1:{port}")
        rp = RemotePieces(f"127.0.0.1:{port}", entries)
        got = rp.get_many(list(entries))
        total += sum(a.nbytes for a in got.values())
        rp.close()
    best = min(best, time.perf_counter() - t0)
print(total, best, flush=True)
"""


def _p2p_env() -> dict:
    import os

    # the helper processes only move host bytes — keep them off the
    # TPU tunnel entirely
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def _p2p_spawn_servers(n: int, n_pieces: int, rows: int):
    import subprocess
    import sys as _sys

    procs, ports = [], []
    try:
        for i in range(n):
            p = subprocess.Popen(
                [
                    _sys.executable, "-c", _P2P_SERVER_SRC,
                    str(i), str(n_pieces), str(rows),
                ],
                stdout=subprocess.PIPE, env=_p2p_env(), text=True,
            )
            procs.append(p)
        for p in procs:
            ports.append(int(p.stdout.readline()))
    except Exception:
        # a server that died before printing its port must not leave
        # the others sleeping with ~0.5 GB resident each
        for p in procs:
            p.kill()
        raise
    return procs, ports


def _p2p_bench() -> dict:
    """Shard-plane throughput, measured in the production topology —
    the serving worker is a SEPARATE PROCESS (an in-process loopback
    measurement shares one GIL between both ends and understates the
    plane ~2x). Two numbers (VERDICT r4 #1):

    - ``p2p_bw_gbs``: one fetcher draining one peer's ~128 MB snapshot
      through the pooled pipelined FETCHN path — the single-link rate
      the migration stall model uses;
    - ``p2p_agg_bw_gbs``: 4 fetcher processes × 4 server processes
      (every fetcher drains every server — the all-to-all shape of a
      real mesh migration restore), aggregate bytes over the slowest
      fetcher's wall clock. This is what a v5e-pod restore scales by.
    """
    import subprocess
    import sys as _sys

    from edl_tpu.runtime import checkpoint as ck
    from edl_tpu.runtime.shard_server import RemotePieces, fetch_index

    # --- single peer, one fetcher (this process) ---
    # ~512 MB snapshot: a migration moves GBs per host, so the bench
    # payload must amortize the one-shot costs a real restore amortizes
    # (connects, buffer autotuning, first-touch page faults) — 128 MB
    # under-reports the plane ~2x
    procs, ports = _p2p_spawn_servers(1, n_pieces=16, rows=8192)
    try:
        _, entries = fetch_index(f"127.0.0.1:{ports[0]}")
        total = 0
        best = float("inf")
        for _ in range(3):
            rp = RemotePieces(f"127.0.0.1:{ports[0]}", entries)
            t0 = time.perf_counter()
            got = rp.get_many(list(entries))
            best = min(best, time.perf_counter() - t0)
            total = sum(a.nbytes for a in got.values())
            rp.close()
    finally:
        for p in procs:
            p.kill()
    bw = total / best

    # --- aggregate: 4 fetcher procs x 4 server procs, all-to-all ---
    n_srv, n_fetch = 4, 4
    procs, ports = _p2p_spawn_servers(n_srv, n_pieces=4, rows=8192)
    fetchers = []
    try:
        port_arg = ",".join(str(p) for p in ports)
        fetchers = [
            subprocess.Popen(
                [_sys.executable, "-c", _P2P_FETCHER_SRC, port_arg, "2"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=_p2p_env(), text=True,
            )
            for _ in range(n_fetch)
        ]
        agg_bytes = 0
        worst = 0.0
        for f in fetchers:
            out = f.stdout.readline().split()
            if len(out) != 2:
                # a dead fetcher's real traceback, not an IndexError
                raise RuntimeError(
                    f"p2p fetcher died: {f.stderr.read()[-500:]}"
                )
            agg_bytes += int(out[0])
            worst = max(worst, float(out[1]))
            f.wait(timeout=30)
    finally:
        for p in procs + fetchers:
            p.kill()
    agg_bw = agg_bytes / worst if worst else 0.0

    return {
        "p2p_bw_gbs": round(bw / (1 << 30), 3),
        "p2p_agg_bw_gbs": round(agg_bw / (1 << 30), 3),
        "stall_model_8b_migrate_s": round(
            ck.p2p_migrate_stall_model(17 * (1 << 30), 1, bw), 1
        ),
    }


def _elasticity_bench() -> dict:
    """Train⇄serve elasticity rung: one full run of the chip-handover
    demo (scripts/exp_elasticity.py — broker + controller + live
    trainer + real warm-started replica fleet over two diurnal cycles)
    in a subprocess, publishing the printed ``ELASTICITY_MEASURE``
    figures:

    - ``elasticity_handover_stall_s`` — worst traffic-stopping trainer
      reshard inside a handover (the lease-driven twin of
      ``reshard_stall_s``);
    - ``elasticity_grant_ready_s`` — chip grant → replica READY ramp,
      dominated by the warm spawn (process boot + p2p pull + compile);
    - ``elasticity_warm_fetch_s`` / ``elasticity_cold_load_s`` — the
      p2p weight pull vs the cold export+load disk round trip for the
      same tree (the satellite comparison; cold rides ungated).

    A failed or timed-out demo publishes ``-1.0`` sentinels — the perf
    gate reports them as skipped, never as a silent pass."""
    import os
    import subprocess
    import sys as _sys

    out = {
        "elasticity_handover_stall_s": -1.0,
        "elasticity_grant_ready_s": -1.0,
        "elasticity_warm_fetch_s": -1.0,
        "elasticity_cold_load_s": -1.0,
        "elasticity_config": "pool8/train6/cpr2/h48",
    }
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts", "exp_elasticity.py",
    )
    try:
        res = subprocess.run(
            [_sys.executable, script, "--dryrun", "--seed", "0"],
            capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        return out
    if res.returncode != 0:
        return out
    for line in res.stdout.splitlines():
        if not line.startswith("ELASTICITY_MEASURE "):
            continue
        for part in line.split()[1:]:
            k, _, v = part.partition("=")
            key = f"elasticity_{k}" if not k.startswith("elasticity") else k
            if key.removeprefix("elasticity_") in (
                "handover_stall_s", "grant_ready_s", "warm_fetch_s",
                "cold_load_s",
            ):
                out[key] = float(v)
    return out


def _peak_hbm_bw(device) -> float:
    """Per-chip HBM bandwidth (bytes/s) — the decode roofline
    denominator, from the shared peak table (obs/costmodel.py).

    Note: the B=1 decode rung has measured slightly ABOVE 1.0
    pct-of-peak on the bench chip (reported as "TPU v5 lite"), i.e.
    the spec value is conservative for that part — read pct-of-peak as
    a relative efficiency index, not a physical bound."""
    return _costmodel.peak_for_device(device).hbm_bytes_s


def _decode_step_bytes(cfg, param_bytes: int, b: int, s_pad: int) -> float:
    """HBM bytes one decode step must move — delegates to the shared
    cost model (obs/costmodel.py decode_step_bytes: every parameter
    byte plus the FULL padded KV cache; tests/test_costmodel.py pins
    the call sites agree)."""
    return _costmodel.decode_step_bytes(cfg, param_bytes, b, s_pad)


def measure_decode(gen_params, cfg, b, t0, max_new, reps=None):
    """(prefill_s, per_tok_s or None) for one decode-ladder rung, by
    DIFFERENCING two generation lengths: both programs share an
    identical prefill + cache build, so the per-run tunnel jitter on
    the prefill cancels out of the steady-state decode rate (a
    prefill-subtraction estimate swung >50% between bench runs);
    prefill_s is then derived by extrapolating the decode cost back
    out of the short run.

    Module-level so `scripts/exp_int8_decode.py` runs the SAME harness
    as the published numbers — a private copy there already diverged
    once (rep counts) before this was shared.

    Bias note: the two programs pad their KV caches to different
    max_len (t0+short vs t0+long_), so the long run's decode steps
    attend over a slightly larger S — per_tok is a small systematic
    OVERestimate (conservative direction) at these sizes, not a
    cancellation-breaking error."""
    from edl_tpu.models import llama

    if reps is None:
        # B=1 runs are short enough that tunnel jitter competes with
        # the signal — buy stability with extra (cheap) reps. Lives
        # HERE so every caller shares one rep policy.
        reps = 5 if b == 1 else 3
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab, (b, t0), np.int32)
    )
    short, long_ = max_new // 2, max_new + max_new // 2

    def timed_gen(n):
        toks = llama.generate(gen_params, prompt, cfg, max_new=n)
        int(np.asarray(toks)[0, -1])  # compile + dependent-fetch fence
        best = float("inf")
        for _ in range(reps):
            t1 = time.perf_counter()
            toks = llama.generate(gen_params, prompt, cfg, max_new=n)
            int(np.asarray(toks)[0, -1])
            best = min(best, time.perf_counter() - t1)
        return best

    t_short = timed_gen(short)
    t_long = timed_gen(long_)
    if t_long <= t_short * 1.02:
        return -1.0, None  # tunnel jitter swamped the window
    per_tok = (t_long - t_short) / (long_ - short)
    prefill_s = t_short - short * per_tok
    return (prefill_s if prefill_s >= 0 else -1.0), per_tok


def _llama_decode_bench() -> dict:
    """Serving-path metrics for the KV-cache decode (runtime/export.py
    consumer; VERDICT r3 #3): prefill latency, steady-state decode
    tokens/s, and — VERDICT r4 #3 — the HBM-bandwidth roofline
    accounting for each point of a small batch ladder
    (``decode_pct_peak_bw``: achieved bytes/s over the chip's peak;
    decode moves every weight byte plus the whole padded cache per
    step, so %-of-peak IS the efficiency of the decode program). Same
    flagship architecture as the train bench, bf16 params (the export
    dtype), no remat — inference holds no optimizer state. Greedy
    decode: the generate program is one jit (prefill + lax.scan over
    positions), so the measured rate includes cache updates and
    sampling, not per-token dispatch."""
    from edl_tpu.models import llama

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        # max_new 128 -> a 128-step differencing window: the 64-step
        # window swung up to 4x between runs under tunnel jitter (a
        # 4.35x "win" that re-measured at 1.45x)
        ladder = [(1, 512, 128), (8, 512, 128), (32, 512, 128)]
        headline = 8
    else:
        cfg = llama.LlamaConfig(
            vocab=1024, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=384, dtype=jnp.float32,
        )
        ladder = [(2, 32, 8)]
        headline = 2
    # bf16 params: what load_export hands a serving process
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if on_tpu else x,
        jax.jit(lambda: llama.init_params(jax.random.PRNGKey(2), cfg))(),
    )
    peak_bw = _peak_hbm_bw(jax.devices()[0])
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
    )

    def measure(b, t0, max_new, gen_params=None):
        return measure_decode(
            params if gen_params is None else gen_params,
            cfg, b, t0, max_new,
        )

    out: dict = {}
    rungs = []
    for b, t0, max_new in ladder:
        prefill_s, per_tok = measure(b, t0, max_new)
        if per_tok is None:
            rungs.append({
                "b": b, "t0": t0,
                "decode_tokens_per_sec": -1.0,
                "decode_pct_peak_bw": -1.0,  # consistent rung schema
            })
            if b == headline:
                out.update({
                    "prefill_s": -1.0,
                    "decode_tokens_per_sec": -1.0,
                    "decode_pct_peak_bw": -1.0,
                    "decode_config": f"B{b}/T0{t0}:jitter",
                })
            continue
        # roofline: bytes the step MUST move over the measured step
        # time. Only meaningful against a TPU's HBM — the CPU smoke
        # path publishes the explicit -1.0 marker, same policy as the
        # jitter branch (never a plausible-looking nonsense number).
        s_pad = t0 + max_new + max_new // 2  # the long program's padding
        pct = (
            _decode_step_bytes(cfg, param_bytes, b, s_pad) / per_tok / peak_bw
            if on_tpu
            else -1.0
        )
        rung = {
            "b": b,
            "t0": t0,
            "decode_tokens_per_sec": round(b / per_tok, 1),
            "decode_pct_peak_bw": round(pct, 4),
        }
        rungs.append(rung)
        if b == headline:
            out.update({
                "prefill_s": round(prefill_s, 4),
                "decode_tokens_per_sec": rung["decode_tokens_per_sec"],
                "decode_pct_peak_bw": rung["decode_pct_peak_bw"],
                "decode_config": f"B{b}/T0{t0}/new{max_new//2}-{max_new+max_new//2}",
            })
    out["decode_ladder"] = rungs

    # -- the quantization lever (VERDICT r4 #3): weight-only int8 ------
    # Decode streams every matmul-weight byte per token; int8 halves
    # exactly that term and nothing else, so the lever pays where the
    # weight stream dominates the step — B=1 latency serving (measured
    # 2.7x on this chip) — and fades once the KV cache and attention
    # math amortize it away (1.08x at B=8, 1.05x at B=32; decomposition
    # in scripts/exp_int8_decode.py). Both the latency rung and the
    # headline rung are published so the fade is visible, with the
    # roofline denominator re-counting the quantized tree's actual
    # bytes.
    qparams = jax.jit(llama.quantize_params_int8)(params)
    q_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(qparams)
    )
    base_rate = {r["b"]: r["decode_tokens_per_sec"] for r in rungs}
    for b, t0, max_new in ladder:
        if b not in (1, headline):
            continue
        prefill_q, per_tok_q = measure(b, t0, max_new, gen_params=qparams)
        suffix = "" if b == headline else "_b1"
        # failed-measurement sentinel policy: every key the success
        # path writes exists with an explicit -1.0, never absent
        if per_tok_q is None:
            out.update({
                f"decode_int8{suffix}_tokens_per_sec": -1.0,
                f"decode_int8{suffix}_pct_peak_bw": -1.0,
                f"decode_int8{suffix}_speedup": -1.0,
            })
            continue
        s_pad = t0 + max_new + max_new // 2
        pct_q = (
            _decode_step_bytes(cfg, q_bytes, b, s_pad) / per_tok_q / peak_bw
            if on_tpu
            else -1.0
        )
        rate = round(b / per_tok_q, 1)
        out.update({
            f"decode_int8{suffix}_tokens_per_sec": rate,
            f"decode_int8{suffix}_pct_peak_bw": (
                round(pct_q, 4) if on_tpu else -1.0
            ),
        })
        base = base_rate.get(b, -1.0)
        out[f"decode_int8{suffix}_speedup"] = (
            round(rate / base, 3) if base and base > 0 else -1.0
        )
    del params, qparams
    jax.clear_caches()
    return out


def _llama_serving_bench() -> dict:
    """Serving-engine rung: the continuous-batching engine end to end
    (admission + fused horizon decode + donated-cache updates + the
    double-buffered drain), not just the raw decode program the ladder
    above times. Publishes aggregate tokens/s at horizon 1 vs 8 on a
    fixed decode-heavy workload plus dispatches/token at H=8 — the
    dispatch-amortization headline the fused loop exists for. Uses the
    exp_serving harness functions so the bench and the soak script
    cannot drift apart."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from edl_tpu.models import llama
    from scripts.exp_serving import build_workload, run_workload

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        n_requests, slots, max_len = 12, 8, 256
    else:
        cfg = llama.LlamaConfig.tiny(vocab=512)
        n_requests, slots, max_len = 6, 4, 96
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(4), cfg))()
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params
        )
    reqs = build_workload(
        n_requests, cfg.vocab, np.random.RandomState(7), on_tpu, deep=True
    )
    out: dict = {}
    rate = {}
    for h in (1, 8):
        run_workload(params, cfg, reqs, slots, max_len, horizon=h)  # compile
        elapsed, tokens, metrics = run_workload(
            params, cfg, reqs, slots, max_len, horizon=h
        )
        snap = metrics.snapshot()
        rate[h] = tokens / elapsed if elapsed > 0 else -1.0
        out[f"serving_tokens_per_sec_h{h}"] = round(rate[h], 1)
        out[f"serving_dispatches_per_token_h{h}"] = round(
            snap["dispatches_per_token"], 4
        )
    out["serving_horizon_speedup"] = (
        round(rate[8] / rate[1], 3) if rate[1] > 0 else -1.0
    )
    out["serving_config"] = f"slots{slots}/req{n_requests}"
    del params
    jax.clear_caches()
    return out


def _llama_goodput_bench() -> dict:
    """SLO-goodput rung: a seeded bursty multi-tenant workload
    (serving/loadgen.py — the same generator `edl loadgen` and the
    soak harness use) replayed WALL-CLOCK against the engine, scored
    by obs/slo.py. Publishes goodput req/s (requests meeting their
    class TTFT+TPOT SLOs — the number a serving scheduler should be
    judged by, per DistServe), TTFT SLO attainment, and the p99 queue
    wait from the latency decomposition — the three figures the
    ROADMAP's scheduler upgrades (priority classes, fairness,
    preemption) must move."""
    from edl_tpu.models import llama
    from edl_tpu.obs import slo
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving import loadgen
    from edl_tpu.serving.engine import ContinuousBatchingEngine
    from edl_tpu.serving.metrics import ServingMetrics

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        n_requests, slots, max_len, rate = 48, 8, 256, 8.0
    else:
        cfg = llama.LlamaConfig.tiny(vocab=512)
        n_requests, slots, max_len, rate = 16, 4, 96, 12.0
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(4), cfg))()
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params
        )
    classes = slo.default_classes(1.0, 0.25)
    spec = loadgen.WorkloadSpec(
        seed=0, n_requests=n_requests, rate_rps=rate, arrival="burst",
        vocab=cfg.vocab, classes=classes,
    )
    reqs = loadgen.build(spec)

    def _run():
        metrics = ServingMetrics(registry=MetricsRegistry())
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=slots, max_len=max_len, horizon=4,
            metrics=metrics,
        )
        res = loadgen.replay(eng, reqs)
        return slo.compute_goodput(
            slo.request_records(metrics), spec.class_map(), res["wall_s"]
        )

    _run()  # pass 1 pays the jit compiles (block + prefill buckets)
    report = _run()
    out = {
        "serving_goodput_rps": round(report["goodput_rps"], 2),
        "serving_ttft_slo_attainment": round(
            report["ttft_slo_attainment"], 4
        ),
        "serving_queue_wait_p99_s": round(
            report["phases"]["queue_wait_s"]["p99"], 4
        ),
        "serving_goodput_config": (
            f"slots{slots}/req{n_requests}/rate{rate:g}/{spec.arrival}"
        ),
    }
    del params
    jax.clear_caches()
    return out


def _llama_paged_bench() -> dict:
    """Paged-KV rung: the two numbers the block pool exists for.

    * ``serving_effective_concurrency_at_fixed_hbm`` — peak concurrent
      requests the PAGED engine holds over a seeded heavy-tailed
      workload, divided by the contiguous engine's capacity at the
      SAME KV HBM budget (the pool is sized to exactly the contiguous
      slots x max_len slab, + the scratch block). Contiguous must
      reserve max_len per slot, so its capacity IS its slot count;
      paged admits on free blocks, so short requests pack. The paper's
      claim is > 1.5x.
    * ``serving_prefix_hit_ttft_ms`` — TTFT of a warm full-prefix hit
      (identical multi-block prompt served twice through a
      prefix-cached engine): admission skips straight past the shared
      blocks, so this should sit well under the cold prefill TTFT
      (published alongside for context, ungated).
    """
    from edl_tpu.models import llama
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving.engine import ContinuousBatchingEngine
    from edl_tpu.serving.metrics import ServingMetrics

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        slots, max_len, bs = 8, 256, 16
    else:
        cfg = llama.LlamaConfig.tiny(vocab=512)
        slots, max_len, bs = 4, 96, 8
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(4), cfg))()
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params
        )
    m = max_len // bs
    pool_blocks = slots * m + 1  # == contiguous slab bytes (+ scratch)

    # heavy-tailed workload: mostly short requests (the regime paging
    # wins — contiguous strands max_len-plen tokens per slot), a deep
    # tail so growth/eviction is exercised. Seeded; counts, not clocks.
    rng = np.random.RandomState(11)
    n_requests = 4 * slots
    reqs = []
    for i in range(n_requests):
        deep = bool(rng.rand() < 0.15)
        plen = int(rng.randint(12, 24) if deep else rng.randint(3, 7))
        budget = int(rng.randint(40, 56) if deep else rng.randint(6, 14))
        prompt = [int(x) for x in rng.randint(0, cfg.vocab, plen)]
        reqs.append((f"pg{i}", prompt, budget))

    def peak_concurrency(**kw):
        eng = ContinuousBatchingEngine(
            params, cfg, max_len=max_len, horizon=4,
            metrics=ServingMetrics(registry=MetricsRegistry()), **kw
        )
        for rid, prompt, budget in reqs:
            eng.submit(rid, prompt, budget)
        peak = 0
        while eng.has_work:
            eng.step()
            peak = max(peak, sum(1 for s in eng._slots if s is not None))
        assert len(eng.results) == n_requests, "paged bench lost requests"
        return peak

    base = peak_concurrency(max_slots=slots)
    packed = peak_concurrency(
        max_slots=4 * slots, block_size=bs, pool_blocks=pool_blocks
    )
    out: dict = {
        "serving_effective_concurrency_at_fixed_hbm": round(
            packed / base, 3
        ),
    }

    def ttft_pair():
        metrics = ServingMetrics(registry=MetricsRegistry())
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=2, max_len=max_len, horizon=4,
            metrics=metrics, block_size=bs, prefix_cache=True,
            prefill_chunk=bs,
        )
        prompt = [(7 * i + 3) % cfg.vocab for i in range(4 * bs)]
        for rid in ("ttft-cold", "ttft-warm"):
            eng.submit(rid, prompt, 6)
            while eng.has_work:
                eng.step()
        return (
            metrics.request_stats("ttft-cold")["ttft_s"],
            metrics.request_stats("ttft-warm")["ttft_s"],
        )

    ttft_pair()  # pass 1 pays the paged prefill/chunk/copy compiles
    cold_s, warm_s = ttft_pair()
    out["serving_prefix_ttft_cold_ms"] = round(cold_s * 1e3, 3)
    out["serving_prefix_hit_ttft_ms"] = round(warm_s * 1e3, 3)
    out["serving_paged_config"] = (
        f"slots{slots}/bs{bs}/pool{pool_blocks}/req{n_requests}"
    )
    del params
    jax.clear_caches()
    return out


def _llama_spec_bench() -> dict:
    """Speculative-decoding rung: b=1 greedy decode with the fused
    draft–verify loop (`--spec-k`). BENCH_r05 put int8 b=1 decode at
    ~99.5% of peak HBM bandwidth — the weight stream is saturated, so
    the only remaining lever is landing >1 token per weight pass.
    Publishes, on a repetitive-prompt workload the n-gram drafter can
    lock onto:

    * ``serving_spec_b1_tokens_per_sec`` — wall-clock single-stream
      decode rate with speculation on.
    * ``serving_spec_accepted_per_dispatch`` — emitted tokens per
      decode-phase dispatch (verify + fallback decode); 1.0 is the
      sequential floor, anything above is tokens the verify program
      landed for free inside one weight pass.

    The non-speculative b=1 rate rides along ungated for context (the
    speedup is workload-dependent: acceptance on adversarial text is
    ~0, and the gated per-dispatch figure already isolates the
    mechanism from drafter luck)."""
    from edl_tpu.models import llama
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving.engine import ContinuousBatchingEngine
    from edl_tpu.serving.metrics import ServingMetrics

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        max_len, max_new, spec_k = 256, 160, 8
    else:
        cfg = llama.LlamaConfig.tiny(vocab=512)
        max_len, max_new, spec_k = 96, 80, 4
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(4), cfg))()
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params
        )
    # short-period prompt: greedy decode on a fixed model settles into
    # a cycle, and the suffix n-gram drafter proposes the continuation
    # — the regime prompt-lookup decoding exists for (code, RAG, edits)
    prompt = [5, 9] * 6

    def _run(k: int):
        metrics = ServingMetrics(registry=MetricsRegistry())
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=1, max_len=max_len, horizon=1,
            metrics=metrics, spec_k=k, spec_ngram=3,
        )
        eng.submit("spec-b1", prompt, max_new)
        t0 = time.perf_counter()
        eng.run()
        elapsed = time.perf_counter() - t0
        return elapsed, len(eng.results["spec-b1"].tokens), metrics.snapshot()

    out: dict = {}
    _run(spec_k)  # pass 1 pays the verify-program compile
    elapsed, tokens, snap = _run(spec_k)
    decode_d = snap["dispatches_decode"] + snap["dispatches_verify"]
    out["serving_spec_b1_tokens_per_sec"] = round(
        tokens / elapsed if elapsed > 0 else -1.0, 1
    )
    out["serving_spec_accepted_per_dispatch"] = round(
        snap["tokens_out"] / decode_d if decode_d else -1.0, 3
    )
    out["serving_spec_acceptance_rate"] = round(
        snap["spec_acceptance_rate"], 3
    )
    _run(0)  # baseline compile (plain decode program at b=1)
    b_elapsed, b_tokens, _ = _run(0)
    out["serving_spec_b1_baseline_tokens_per_sec"] = round(
        b_tokens / b_elapsed if b_elapsed > 0 else -1.0, 1
    )
    out["serving_spec_config"] = f"b1/k{spec_k}/new{max_new}"
    del params
    jax.clear_caches()
    return out


def _llama_kvq_bench() -> dict:
    """Quantized paged-KV rung (``--kv-quant int8``): decode gets
    faster only by moving fewer bytes, so the rung publishes exactly
    the byte ledger plus the wall clock it buys.

    * ``decode_kvq8_b1_tokens_per_sec`` — wall-clock single-stream
      paged decode rate with int8 KV (the bf16-KV rate rides along
      ungated for context; the speedup only materialises where HBM
      bandwidth is the binding resource, i.e. on TPU at depth — on
      CPU the dequant arithmetic can even cost more than the bytes
      save).
    * ``serving_kvq_concurrency_at_fixed_hbm`` — peak concurrent
      requests the int8-KV engine holds over a seeded multi-block
      workload, divided by the bf16-KV paged engine's peak at the SAME
      pool byte budget (the int8 pool converts the identical byte
      allowance into ~2x the blocks after scale overhead, ~4x where
      the baseline pool is f32). Counts, not clocks; the claim is
      >= 1.8x.
    * ``decode_kvq8_bytes_moved_ratio`` — analytic decode-step bytes
      (obs/costmodel.py decode_step_bytes, int8 weights) bf16-KV over
      int8-KV at the flagship long-context serving shape, where the KV
      stream rivals the weight stream. Pure arithmetic, deterministic
      on every platform — the mechanism behind the >= 1.3x tokens/s
      criterion, pinned independently of drafter/platform luck.
    """
    from edl_tpu.models import llama
    from edl_tpu.obs import costmodel as _cm
    from edl_tpu.obs.metrics import MetricsRegistry
    from edl_tpu.serving.engine import ContinuousBatchingEngine
    from edl_tpu.serving.metrics import ServingMetrics

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = flagship_decode_config()
        slots, max_len, bs, max_new = 8, 256, 16, 160
    else:
        cfg = llama.LlamaConfig.tiny(vocab=512)
        slots, max_len, bs, max_new = 4, 96, 8, 80
    params = jax.jit(lambda: llama.init_params(jax.random.PRNGKey(4), cfg))()
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params
        )
    m = max_len // bs
    out: dict = {}

    # -- b=1 wall clock, int8 KV vs bf16 KV, same paged program shape
    def b1_rate(kv_quant: str):
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=1, max_len=max_len, horizon=4,
            metrics=ServingMetrics(registry=MetricsRegistry()),
            block_size=bs, pool_blocks=m + 1, kv_quant=kv_quant,
        )
        eng.submit("kvq-b1", [5, 9, 2, 11], max_new)
        t0 = time.perf_counter()
        eng.run()
        elapsed = time.perf_counter() - t0
        return elapsed, len(eng.results["kvq-b1"].tokens)

    b1_rate("int8")  # pass 1 pays the quantized block/prefill compiles
    q_elapsed, q_tokens = b1_rate("int8")
    b1_rate("off")  # baseline compiles
    f_elapsed, f_tokens = b1_rate("off")
    out["decode_kvq8_b1_tokens_per_sec"] = round(
        q_tokens / q_elapsed if q_elapsed > 0 else -1.0, 1
    )
    out["decode_kvq8_b1_baseline_tokens_per_sec"] = round(
        f_tokens / f_elapsed if f_elapsed > 0 else -1.0, 1
    )

    # -- concurrency at a FIXED pool byte budget: price the bf16 pool,
    # then let int8 spend the identical allowance on more blocks
    # (values at 1 B/el + per-block-per-head f32 scales)
    L, kvh, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    el = 2 if on_tpu else np.dtype(cfg.dtype).itemsize
    base_blocks = slots * m + 1
    per_block_f = 2 * L * bs * kvh * hd * el
    hdp = llama.kvq_packed_head_dim("int8", hd)
    per_block_q = 2 * L * bs * kvh * hdp * 1 + 2 * L * kvh * 4
    q_blocks = (base_blocks * per_block_f) // per_block_q

    # multi-block prompts + long decode budgets make RESIDENCY
    # pool-gated (short prompts admit on one block each and fast-churn
    # budgets finish before occupancy builds, so the pool never
    # binds): every request holds blocks_for(plen) blocks up front and
    # grows for many steps, so peak concurrency is the pool byte
    # budget made visible. Seeded; counts, not clocks.
    rng = np.random.RandomState(13)
    big_slots = 6 * slots
    n_requests = 8 * slots
    reqs = []
    for i in range(n_requests):
        plen = int(rng.randint(3 * bs + 2, 4 * bs - 1))
        prompt = [int(x) for x in rng.randint(0, cfg.vocab, plen)]
        reqs.append((f"kvq{i}", prompt, int(rng.randint(24, 40))))

    def peak_concurrency(kv_quant: str, pool: int) -> int:
        eng = ContinuousBatchingEngine(
            params, cfg, max_slots=big_slots, max_len=max_len, horizon=4,
            metrics=ServingMetrics(registry=MetricsRegistry()),
            block_size=bs, pool_blocks=pool, kv_quant=kv_quant,
        )
        for rid, prompt, budget in reqs:
            eng.submit(rid, prompt, budget)
        peak = 0
        while eng.has_work:
            eng.step()
            peak = max(peak, sum(1 for s in eng._slots if s is not None))
        assert len(eng.results) == n_requests, "kvq bench lost requests"
        return peak

    base_peak = peak_concurrency("off", base_blocks)
    q_peak = peak_concurrency("int8", min(q_blocks, big_slots * m + 1))
    out["serving_kvq_concurrency_at_fixed_hbm"] = round(
        q_peak / base_peak if base_peak else -1.0, 3
    )

    # -- the byte ledger itself: flagship long-context decode step,
    # int8 weights, bf16 KV vs int8 KV (+ scale planes). Deterministic
    # arithmetic from the shared cost model — no clocks involved.
    fcfg = flagship_decode_config()
    fpb = _cm.param_bytes(fcfg, 1)
    fb, fs = 32, 2048
    bytes_bf16 = _cm.decode_step_bytes(fcfg, fpb, fb, fs)
    bytes_q8 = _cm.decode_step_bytes(
        fcfg, fpb, fb, fs,
        kv_bytes_per_el=_cm.kv_quant_bytes_per_el("int8"), kv_block_size=16,
    )
    out["decode_kvq8_bytes_moved_ratio"] = round(bytes_bf16 / bytes_q8, 3)

    out["kv_quant_config"] = (
        f"int8/slots{big_slots}/bs{bs}/poolB{base_blocks * per_block_f}"
        f"/req{n_requests}/fB{fb}xS{fs}/{'tpu' if on_tpu else 'cpu'}"
    )
    del params
    jax.clear_caches()
    return out


def main() -> None:
    n_dev = len(jax.devices())
    plan = MeshPlan.data_parallel(n_dev)
    mesh = plan.build()

    params = ctr.init_params(jax.random.PRNGKey(0))  # full-size: 2^20 vocab
    tx = optax.adam(1e-3)
    state = shard_state(TrainState.create(params, tx), plan, mesh)

    rng = np.random.RandomState(0)
    raw = [ctr.synthetic_batch(rng, BATCH) for _ in range(4)]
    # steps-fused chunk: one dispatch per CHUNK steps (the per-dispatch
    # overhead on a host-driven chip is ~1 ms); the whole bench drives
    # this one program, so only one expensive XLA compile is paid
    stacked = stack_batches(
        [raw[i % len(raw)] for i in range(CHUNK)], plan, mesh
    )
    multi = make_train_multistep(ctr.make_loss_fn(jnp.bfloat16), tx, plan, mesh)

    # NOTE: on tunneled backends block_until_ready can return before the
    # device work completes; a scalar value fetch is the reliable fence.
    t_compile = time.perf_counter()
    state, m = multi(state, stacked)
    float(m["loss"])  # fence: compile + first chunk
    compile_s = time.perf_counter() - t_compile
    for _ in range(WARMUP):
        state, m = multi(state, stacked)
    float(m["loss"])

    # fence ONCE per measure loop (chunks stay pipelined, as in a real
    # training loop — a fence per chunk would serialize a host RTT into
    # every chunk); best of 3 loops suppresses tunnel jitter, and the
    # median/spread ride along as variance evidence (VERDICT r2 Weak #1)
    loop_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(MEASURE // CHUNK):
            state, m = multi(state, stacked)
        float(m["loss"])  # scalar fetch fences the dependent chain
        dt = time.perf_counter() - t0
        loop_rates.append(BATCH * (MEASURE // CHUNK) * CHUNK / dt / n_dev)
    loop_rates = np.asarray(loop_rates)
    eps_per_chip = float(loop_rates.max())
    ctr_median = float(np.median(loop_rates))
    ctr_spread_pct = float(
        100 * (loop_rates.max() - loop_rates.min()) / loop_rates.max()
    )

    # reshard stall, both protocol paths on this chip, min of 2 runs
    # (host<->device bandwidth on a tunneled chip is noisy; min is the
    # standard interference-suppressing estimator):
    # fast path — direct device-to-device re-placement (what an elastic
    # rescale uses when device sets overlap; rides ICI on multi-chip)
    from edl_tpu.runtime.elastic import _device_reshard

    stall_fast_s = stall_host_s = float("inf")
    state2 = state
    for _ in range(2):
        t1 = time.perf_counter()
        state2 = _device_reshard(state2, plan, mesh, None)
        float(jnp.sum(state2.params["out"]["b"]))
        stall_fast_s = min(stall_fast_s, time.perf_counter() - t1)
    # fallback path — host-RAM staging (worst case: disjoint devices),
    # down/up overlapped in one pipeline. Measured twice: f32 (no
    # compression — the RAW link-bandwidth reference) and the int8
    # moment-staging default (the production stall; ops/quant.py).
    stall_host_f32_s = float("inf")
    state3 = state2
    for _ in range(2):
        t2 = time.perf_counter()
        state3 = ckpt.staged_reshard(state3, plan, mesh, stage="f32")
        float(jnp.sum(state3.params["out"]["b"]))
        stall_host_f32_s = min(stall_host_f32_s, time.perf_counter() - t2)
    for _ in range(2):
        t2 = time.perf_counter()
        state3 = ckpt.staged_reshard(state3, plan, mesh, stage="int8")
        float(jnp.sum(state3.params["out"]["b"]))
        stall_host_s = min(stall_host_s, time.perf_counter() - t2)
    # per-host staging bandwidth, derived from the CTR staging above
    # (its ~100s-of-MB state amortizes link latency) — powers the
    # worst-case shrink model of doc/reshard_stall.md (VERDICT r1 #7).
    # On a multi-host slice every host stages its own 1/H share
    # concurrently during the measured stall.
    ctr_state_b = ckpt.state_nbytes(state3)
    ctr_moment_b = ckpt.state_nbytes(state3.opt_state)
    n_hosts = max(jax.process_count(), 1)
    # RAW link bandwidth from the UNCOMPRESSED (f32) staging run — the
    # int8 headline stall must not inflate the bandwidth the 8B model
    # extrapolates with (its state is params-dominated)
    host_bw = (
        ctr_state_b / n_hosts / stall_host_f32_s
        if stall_host_f32_s > 0
        else 0.0
    )
    # BASELINE config #5 shrink bound: Llama-3-8B FSDP state (bf16
    # params + adafactor factored moments ~= 17 GB, ~1 GB moments)
    # landing on ONE surviving v5e host; <30 s is the budget on
    # production PCIe links (a tunneled dev chip measures ~0.01 GB/s
    # and fails it — expected)
    model_8b_s = (
        ckpt.host_fallback_stall_model(
            17 * (1 << 30),
            hosts_after=1,
            host_bw_bytes_s=host_bw,
            moment_bytes=1 << 30,
            stage="int8",
        )
        if host_bw
        else -1.0
    )
    del state, state2, state3, stacked  # free HBM for the flagship bench

    # flagship Llama train-step throughput + MFU on a NON-toy config
    # (VERDICT r1 #3: report mfu ≥ 0.40 at ≥d2048/L16, T≥2048, bf16).
    # Runs LAST: its ~14 GB working set would fragment HBM under the
    # reshard-stall measurements above.
    llama_metrics = _llama_flagship_bench(n_dev, plan, mesh, rng)
    llama_metrics.update(_llama_decode_bench())
    llama_metrics.update(_llama_serving_bench())
    llama_metrics.update(_llama_goodput_bench())
    llama_metrics.update(_llama_paged_bench())
    llama_metrics.update(_llama_spec_bench())
    llama_metrics.update(_llama_kvq_bench())
    llama_metrics.update(_p2p_bench())
    llama_metrics.update(_elasticity_bench())

    print(
        json.dumps(
            {
                "metric": "ctr_examples_per_sec_per_chip",
                "value": round(eps_per_chip, 1),
                "unit": "examples/s/chip",
                "vs_baseline": 1.0,
                "ctr_median": round(ctr_median, 1),
                "ctr_spread_pct": round(ctr_spread_pct, 2),
                "reshard_stall_s": round(stall_fast_s, 4),
                "reshard_stall_host_fallback_s": round(stall_host_s, 4),
                "reshard_stall_host_f32_s": round(stall_host_f32_s, 4),
                "reshard_stage": "int8",
                "ctr_moment_mb": round(ctr_moment_b / (1 << 20), 1),
                "host_stage_bw_gbs": round(host_bw / (1 << 30), 3),
                "stall_model_8b_1host_s": round(model_8b_s, 1),
                **llama_metrics,
                "compile_s": round(compile_s, 2),
                "final_loss": round(float(m["loss"]), 4),
                "n_devices": n_dev,
                "platform": jax.devices()[0].platform,
                "global_batch": BATCH,
            }
        )
    )


if __name__ == "__main__":
    main()
