"""Three-job elastic squeeze — the reference's headline demo.

Port of the doc/boss_tutorial.md "Deploy Multiple Training Jobs" trace:
one elastic job grows to fill the idle fleet; each newly submitted job
forces the autoscaler to squeeze the incumbents toward their minimums
until everyone fits; no job ever restarts and pending returns to zero.
(Reference trace: example 10→3, example1 8→4, example2 0→4 with cluster
CPU util 18%→88%.) Here the contended resource is TPU chips.

Run: python examples/elastic_demo.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from edl_tpu.api.job import TrainingJob  # noqa: E402
from edl_tpu.cluster.fake import FakeCluster, FakeHost  # noqa: E402
from edl_tpu.controller.controller import Controller  # noqa: E402
from edl_tpu.monitor.collector import ClusterSource, Collector  # noqa: E402

JOB_TMPL = """
metadata: {{name: {name}}}
spec:
  fault_tolerant: true
  worker:
    entrypoint: "python train.py"
    min_replicas: {min}
    max_replicas: {max}
    resources:
      requests: {{cpu: "1", memory: 1Gi, tpu: {chips}}}
      limits: {{tpu: {chips}}}
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10)
    ap.add_argument("--chips-per-host", type=int, default=4)
    ap.add_argument("--max-load", type=float, default=1.0)
    args = ap.parse_args()

    cluster = FakeCluster(
        hosts=[
            FakeHost(f"h{i}", 16000, 32000, args.chips_per_host)
            for i in range(args.hosts)
        ]
    )
    ctl = Controller(cluster, max_load_desired=args.max_load)
    collector = Collector(ClusterSource(cluster), interval_s=0)

    def settle(note: str, ticks: int = 6):
        for _ in range(ticks):
            cluster.reconcile()
            ctl.autoscaler.tick()
            ctl.step()
        s = collector.poll()
        print(f"---- {note}")
        print(s.render())
        print()
        return s

    settle("idle cluster", ticks=1)

    jobs = [
        ("example", 2, 10, 4),
        ("example1", 2, 8, 4),
        ("example2", 2, 4, 4),
    ]
    samples = []
    for name, lo, hi, chips in jobs:
        job = TrainingJob.from_yaml(
            JOB_TMPL.format(name=name, min=lo, max=hi, chips=chips)
        )
        cluster.submit_job(job)
        samples.append(settle(f"submitted {name} (elastic {lo}..{hi})"))

    final = samples[-1]
    assert not final.pending_jobs, "squeeze must leave no job pending"
    total_busy = sum(final.running_workers.values())
    print(
        f"squeeze complete: workers per job {final.running_workers}, "
        f"{total_busy} workers busy, chip util {final.chip_util:.1f}%"
    )
    # every job got at least its minimum; the first job gave chips back
    for name, lo, _, _ in jobs:
        assert final.running_workers[name] >= lo, name
    assert final.running_workers["example"] < 10
    return 0


if __name__ == "__main__":
    sys.exit(main())
