"""Llama-class FSDP training over a dp×fsdp×tp mesh.

The BASELINE.md flagship config, hardware-free: a tiny Llama trained
with real 3D shardings (batch over dp, parameters/optimizer sharded
over fsdp, attention/MLP heads over tp) on a virtual CPU mesh. On real
hardware the same code spans a multi-host slice: the mesh axes map onto
ICI and `jax.distributed` handles process bootstrap (runtime/entrypoint).

Run: python examples/llama/train.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from edl_tpu.utils.platform import force_virtual_cpu  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--per-shard-batch", type=int, default=2)
    ap.add_argument(
        "--export-dir",
        default="",
        help="publish a servable float32 params-only export at the end "
        "and decode a sample from it (production jobs export bf16 via "
        "EDL_EXPORT_DTYPE; f32 here keeps the tiny demo's decode exact)",
    )
    ap.add_argument(
        "--mesh",
        default="",
        help='MeshPlan.parse override, e.g. "sp=2,dp" (ring attention) '
        'or "pp=2,dp" (GPipe) — default: the job.yaml mesh block',
    )
    args = ap.parse_args()

    force_virtual_cpu(args.devices)

    import jax
    import numpy as np
    import optax

    from edl_tpu.api.job import TrainingJob
    from edl_tpu.models import llama
    from edl_tpu.parallel.mesh import MeshPlan
    from edl_tpu.train.trainer import (
        TrainState,
        global_batch,
        make_train_step,
        shard_state,
    )

    if args.mesh:
        plan = MeshPlan.parse(args.mesh, args.devices)
    else:
        job = TrainingJob.from_yaml_file(
            os.path.join(os.path.dirname(__file__), "job.yaml")
        )
        plan = MeshPlan.create(**job.spec.mesh.axis_sizes())
    mesh = plan.build(jax.devices()[: args.devices])
    print(f"mesh: {plan.describe()}")

    cfg = llama.LlamaConfig.tiny(vocab=1024)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    pspecs = llama.param_pspecs(cfg, plan)
    tx = optax.adamw(3e-4)
    # mesh-aware loss: activates ring/Ulysses attention on an sp axis
    # and the GPipe schedule on a pp axis
    state = shard_state(TrainState.create(params, tx), plan, mesh, pspecs)
    step = make_train_step(
        llama.make_loss_fn(cfg, plan, mesh), tx, plan, mesh, pspecs
    )

    rng = np.random.RandomState(0)
    shards = plan.batch_shards()
    for i in range(args.steps):
        tokens = llama.synthetic_tokens(
            rng, args.per_shard_batch * shards, args.seq, cfg.vocab
        )
        state, metrics = step(state, global_batch(tokens, plan, mesh))
        print(f"step {i}: loss={float(metrics['loss']):.4f}")

    assert int(state.step) == args.steps
    if args.export_dir:
        from edl_tpu.runtime.export import export_params, load_export

        d = export_params(
            args.export_dir, state.params, int(state.step), dtype="float32",
            model_meta=cfg.to_meta(),
        )
        print(f"export published: {d}")
        # the serving round trip: a consumer loads ONLY the export and
        # decodes with the KV cache (llama.generate)
        served, _ = load_export(args.export_dir)
        prompt = np.asarray([[1, 2, 3, 4]], np.int32)
        toks = llama.generate(served, prompt, cfg, max_new=8)
        print(f"generated from export: {np.asarray(toks)[0].tolist()}")
    print("ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
