"""Elastic ResNet training — BASELINE config #3, on the REAL
multi-process runtime, including a graceful scale-DOWN drain.

Starts at the manifest's min+1 workers, drains one mid-run (the
autoscaler-squeeze direction of doc/boss_tutorial.md — the departing
worker keeps stepping until rank 0 publishes the reshard, then exits 0),
and finishes on the smaller mesh with state carried in place.

Run (hardware-free): python examples/resnet/train.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=1536)
    ap.add_argument("--per-worker-batch", type=int, default=16)
    ap.add_argument("--work-dir", default="")
    args = ap.parse_args()

    from edl_tpu.api.job import TrainingJob
    from edl_tpu.api.parser import JobParser
    from edl_tpu.runtime.launcher import ProcessJobLauncher

    job = TrainingJob.from_yaml_file(
        os.path.join(os.path.dirname(__file__), "job.yaml")
    )
    JobParser().validate(job)
    wd = args.work_dir or tempfile.mkdtemp(prefix="resnet_elastic_")
    start = job.spec.worker.min_replicas + 1

    with ProcessJobLauncher(
        job=job.name,
        model="resnet",
        min_workers=job.spec.worker.min_replicas,
        max_workers=job.spec.worker.max_replicas,
        n_samples=args.samples,
        passes=job.spec.passes,
        per_device_batch=args.per_worker_batch,
        step_sleep_s=0.05,
        work_dir=wd,
    ) as launcher:
        launcher.start(start)
        print(f"submitted {job.name}: {start} workers (elastic "
              f"{job.spec.worker.min_replicas}..{job.spec.worker.max_replicas})")
        launcher.wait_progress(3, timeout_s=240)
        print(f"draining down to {start - 1} workers mid-run ...")
        launcher.scale_to(start - 1)
        rcs = launcher.wait(timeout_s=600)
        # the drained worker also exits 0: graceful departure
        assert all(rc == 0 for rc in rcs.values()), rcs
        first = float(launcher.kv("loss_first"))
        last = float(launcher.kv("loss_last"))
        reshards = int(launcher.kv("reshards") or "0")
        print(
            f"done: phase={launcher.kv('phase')} steps={launcher.progress()} "
            f"loss {first:.4f} -> {last:.4f} reshards={reshards}"
        )
        assert launcher.kv("phase") == "succeeded"
        assert reshards >= 1
        assert last < first
    return 0


if __name__ == "__main__":
    sys.exit(main())
