"""Elastic Mixture-of-Experts pretraining — expert parallelism on the
REAL multi-process runtime.

No reference analog (SURVEY §2.5: "Expert parallelism: NO"). The mesh
is "ep=2,dp": every worker process drives 2 virtual chips so the
expert axis spans chips, and the dp axis absorbs elastic membership
change — a mid-run scale-up reshards dp from 1 to 2 while the expert
placement survives (pinned axes ride through the in-place reshard).

Run (hardware-free): python examples/moe/train.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=768)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--per-chip-batch", type=int, default=8)
    ap.add_argument("--step-sleep", type=float, default=0.2,
                    help="per-step throttle so the scale event lands "
                    "mid-run")
    ap.add_argument("--work-dir", default="")
    args = ap.parse_args()

    from edl_tpu.api.job import TrainingJob
    from edl_tpu.api.parser import JobParser
    from edl_tpu.runtime.launcher import ProcessJobLauncher

    job = TrainingJob.from_yaml_file(
        os.path.join(os.path.dirname(__file__), "job.yaml")
    )
    JobParser().validate(job)
    wd = args.work_dir or tempfile.mkdtemp(prefix="moe_elastic_")

    with ProcessJobLauncher(
        job=job.name,
        model="moe",
        mesh=job.spec.mesh.to_mesh_string(),
        min_workers=job.spec.worker.min_replicas,
        max_workers=job.spec.worker.max_replicas,
        n_samples=args.samples,
        passes=job.spec.passes,
        per_device_batch=args.per_chip_batch,
        local_devices=2,  # ep=2 spans this worker's 2 (virtual) chips
        seq_len=args.seq_len,
        ckpt_every=8,
        step_sleep_s=args.step_sleep,
        work_dir=wd,
        extra_env={"EDL_VOCAB": str(args.vocab)},
    ) as launcher:
        launcher.start(job.spec.worker.min_replicas)
        print(
            f"submitted {job.name}: {job.spec.worker.min_replicas} worker(s), "
            f"elastic up to {job.spec.worker.max_replicas}, mesh ep=2,dp"
        )
        launcher.wait_progress(3, timeout_s=240)
        print("scaling up to 2 workers mid-pretraining ...")
        launcher.scale_to(2)
        rcs = launcher.wait(timeout_s=600)
        assert all(rc == 0 for rc in rcs.values()), rcs
        first = float(launcher.kv("loss_first"))
        last = float(launcher.kv("loss_last"))
        reshards = int(launcher.kv("reshards") or "0")
        print(
            f"done: phase={launcher.kv('phase')} steps={launcher.progress()} "
            f"lm_loss {first:.4f} -> {last:.4f} reshards={reshards}"
        )
        assert launcher.kv("phase") == "succeeded"
        assert reshards >= 1
        assert last < first
    return 0


if __name__ == "__main__":
    sys.exit(main())
