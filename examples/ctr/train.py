"""Elastic CTR training — the reference's production workload, on REAL
on-disk data.

Port of reference example/ctr/ctr/train.py:120-235: the Criteo-shaped
deep model (13 dense + 26 categorical features, 2^20-slot embedding,
400x400x400 MLP) trained data-parallel with elastic workers. The
reference's DistributeTranspiler/pserver split becomes an in-mesh DP
trainer; periodic checkpointing replaces save_inference_model; the
per-trainer dataset shard download (reference: ctr/train.py:222-227)
becomes a prepared shard directory (runtime/shards.py) read through the
coordinator's lease queue — any worker can materialize any leased range,
which is what keeps the data plane elastic.

Run (hardware-free): python examples/ctr/train.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from edl_tpu.utils.platform import force_virtual_cpu  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64, help="per-chip batch")
    ap.add_argument("--vocab", type=int, default=2**14,
                    help="embedding slots (2^20 on real hardware)")
    ap.add_argument("--ckpt-every", type=int, default=0,
                    help="checkpoint period in steps (0 = off; "
                    "reference: save_inference_model every 1000 batches)")
    ap.add_argument("--ckpt-dir", default="/tmp/edl-ctr-ckpt")
    ap.add_argument("--export-dir", default="",
                    help="publish a servable params-only export here on "
                    "the ckpt cadence and at the end (reference: "
                    "save_inference_model, ctr/train.py:169-180)")
    ap.add_argument("--data-dir", default="",
                    help="shard-manifest dataset dir; prepared with "
                    "synthetic rows when absent (the reference pre-bakes "
                    "RecordIO shards into the job image)")
    ap.add_argument("--real-data", action="store_true",
                    help="prepare REAL rows (examples/ctr/real_data.py "
                    "encoding of the bundled breast-cancer set) instead "
                    "of synthetic ones; implies their vocab")
    ap.add_argument("--samples", type=int, default=65536)
    ap.add_argument("--sync-every", type=int, default=1,
                    help="delayed-sync DP: K local steps per dp group "
                    "between cross-group averages (the TPU analog of the "
                    "reference's --async_mode, ctr/train.py:75-79); 1 = "
                    "fully synchronous")
    args = ap.parse_args()

    force_virtual_cpu(args.devices)

    import jax
    import numpy as np
    import optax

    from edl_tpu.api.job import JobPhase, TrainingJob
    from edl_tpu.cluster.fake import FakeCluster, FakeHost
    from edl_tpu.controller.controller import Controller
    from edl_tpu.models import ctr
    from edl_tpu.runtime import checkpoint as ckpt
    from edl_tpu.runtime.export import export_params
    from edl_tpu.runtime.data import ElasticDataQueue, QueueBatcher
    from edl_tpu.runtime.local import LocalJobRunner
    from edl_tpu.runtime.shards import FileShardSource, write_shards

    cluster = FakeCluster(
        hosts=[FakeHost(f"h{i}", 16000, 32000, 1) for i in range(args.devices)]
    )
    ctl = Controller(cluster, max_load_desired=1.0)
    job = TrainingJob.from_yaml_file(
        os.path.join(os.path.dirname(__file__), "job.yaml")
    )
    cluster.submit_job(job)
    ctl.step()
    assert ctl.phase_of(job.name) == JobPhase.RUNNING

    rng = np.random.RandomState(0)

    # -- dataset: real files, prepared once (image-prebake analog) ---------
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="ctr_shards_")
    have_manifest = os.path.exists(os.path.join(data_dir, "manifest.json"))
    real_marker = os.path.exists(os.path.join(data_dir, "REAL_DATA"))
    if args.real_data and have_manifest and not real_marker:
        # NEVER silently train "real" on a dir of synthetic shards — a
        # reused --data-dir must match the flag
        print(
            f"--real-data but {data_dir} holds a non-real dataset "
            f"(no REAL_DATA marker); point --data-dir elsewhere",
            file=sys.stderr,
        )
        return 1
    if not have_manifest:
        if args.real_data:
            import real_data

            man = real_data.prepare(data_dir)
            print(
                f"prepared {man['n_samples']} REAL rows of CTR data "
                f"under {data_dir}"
            )
        else:
            rows = ctr.synthetic_batch(rng, args.samples, vocab=args.vocab)
            write_shards(data_dir, rows, shard_size=8192)
            print(f"prepared {args.samples} rows of CTR data under {data_dir}")
    if args.real_data:
        import real_data

        # the model's hash space must match the prepared ids whether
        # the shards were written now or on a previous run
        args.vocab = real_data.VOCAB
    source = FileShardSource(data_dir)
    queue = ElasticDataQueue(
        source.n_samples, chunk_size=512, passes=10**6
    )  # effectively streaming: replay passes until the step budget ends
    batcher = QueueBatcher(queue, source.fetch)

    def data_fn(bs):
        return batcher.next_batch(bs, rollover=True)

    runner = LocalJobRunner(
        ctl,
        job,
        ctr.make_loss_fn(),
        optax.adam(1e-3),
        ctr.init_params(jax.random.PRNGKey(0), vocab=args.vocab),
        per_chip_batch=args.batch,
        sync_every=args.sync_every,
    )

    third = max(args.steps // 3, 1)
    runner.trainer.train_steps(data_fn, third)
    ctl.autoscaler.tick()  # grow into the idle fleet -> in-place reshard
    report = None
    exported = [-1]

    def publish_export(tag=""):
        step_now = int(runner.trainer.state.step)
        if not args.export_dir or step_now <= exported[0]:
            return
        d = export_params(
            args.export_dir, runner.trainer.merged_state.params, step_now
        )
        exported[0] = step_now
        print(f"{tag}export published: {d}")

    for start in range(third, args.steps, third):
        n = min(third, args.steps - start)
        report = runner.trainer.train_steps(data_fn, n)
        if args.ckpt_every and (start + n) % args.ckpt_every < third:
            path = os.path.join(args.ckpt_dir, f"step-{int(runner.trainer.state.step)}")
            ckpt.save(path, runner.trainer.state)
            print(f"checkpoint saved: {path}")
            publish_export()

    stats = queue.progress()
    print(
        f"trained {int(runner.trainer.state.step)} steps on "
        f"{runner.trainer.n_workers} workers: "
        f"loss {report.losses[0]:.4f} -> {report.losses[-1]:.4f}, "
        f"{report.examples_per_sec:.0f} examples/s, "
        f"reshards={[(e.from_workers, e.to_workers) for e in report.reshards]}, "
        f"data: {stats['done']} file chunks acked from {data_dir}"
    )
    publish_export(tag="final ")
    runner.detach()
    return 0


if __name__ == "__main__":
    sys.exit(main())
