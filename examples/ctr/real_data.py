"""Elastic CTR on REAL data — genuine clinical rows in Criteo format,
trained by elastic worker processes, scored by a meaningful AUC.

Reference parity: the reference CTR example downloads a real dataset
per trainer, shards it, and fetches AUC in the train loop
(/root/reference/example/ctr/ctr/train.py:222-227, :161-167). This
environment has zero egress, so "download Criteo" is not on the table;
the largest REAL binary-outcome tabular dataset bundled offline is
scikit-learn's breast-cancer diagnostic set (569 patient records, 30
real-valued features, malignant/benign outcome; Wolberg et al., UCI).
Small, but every row, feature, and label is real — the published AUC
measures a model of the world, not of noise (VERDICT r4 missing #2).

The CTR-format encoding mirrors how Criteo itself is produced:

- ``dense [13]``: the first 13 features, standardized on the TRAIN
  split (Criteo's 13 integer features arrive as raw counts);
- ``sparse [26]``: 26 features quantile-bucketized into 16 bins each
  (bin edges fit on the TRAIN split only — no test leakage), the
  (slot, bin) pair hashed into the embedding space exactly as Criteo's
  26 categorical columns are hashed into theirs;
- ``label``: 1 = malignant (the "event" to rank, ~37% positive).

Pipeline shape is the production one: prepare() writes shard files +
a held-out eval/ split, an elastic multi-process job (worker_main)
trains from the shards through the coordinator's lease queue while
scaling 1 -> 2 workers mid-pass, the commit leader publishes a
held-out AUC per export (``eval_metric`` in KV), and this script
re-scores the final export through ``runtime/predict`` — the same
offline consumer ``edl predict`` drives.

Run:  python examples/ctr/real_data.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

N_BINS = 16  # quantile buckets per sparse slot (Criteo-style hashing)
VOCAB = 1024  # embedding slots (2^20 on real Criteo; 26*16 ids here)


def prepare(data_dir: str, test_fraction: float = 0.2, seed: int = 0) -> dict:
    """Write the real rows as train shards + a held-out eval/ split in
    CTR format (dense [13] f32, sparse [26] i32, label [1] f32)."""
    from sklearn.datasets import load_breast_cancer

    from edl_tpu.models import ctr
    from edl_tpu.runtime import shards

    ds = load_breast_cancer()
    x = ds.data.astype(np.float32)  # [569, 30]
    label = (ds.target == 0).astype(np.float32)  # 1 = malignant event
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(x))
    n_test = max(1, int(len(x) * test_fraction))
    test, train = order[:n_test], order[n_test:]

    # fit all preprocessing on TRAIN rows only
    mu, sd = x[train].mean(0), x[train].std(0) + 1e-8
    dense = ((x - mu) / sd)[:, : ctr.N_DENSE].astype(np.float32)
    qs = np.quantile(
        x[train], np.linspace(0, 1, N_BINS + 1)[1:-1], axis=0
    )  # [N_BINS-1, 30] bin edges per feature
    sparse = np.empty((len(x), ctr.N_SPARSE), np.int32)
    for slot in range(ctr.N_SPARSE):
        feat = slot % x.shape[1]
        bins = np.searchsorted(qs[:, feat], x[:, feat])  # [rows] in [0,16)
        sparse[:, slot] = (slot * N_BINS + bins) % VOCAB

    def rows(idx):
        # label stays FLAT [N] — the ctr loss/AUC contract
        # (models/ctr.py synthetic_batch shape)
        return {
            "dense": dense[idx],
            "sparse": sparse[idx],
            "label": label[idx],
        }

    man = shards.write_shards(data_dir, rows(train), shard_size=64)
    shards.write_shards(
        os.path.join(data_dir, "eval"), rows(test), shard_size=256
    )
    # provenance marker: lets train.py --real-data distinguish this dir
    # from a synthetic one instead of silently training on noise
    with open(os.path.join(data_dir, "REAL_DATA"), "w") as f:
        f.write("breast_cancer_wdbc\n")
    return man


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="")
    ap.add_argument("--passes", type=int, default=6)
    args = ap.parse_args()

    import tempfile

    from edl_tpu.runtime.launcher import ProcessJobLauncher
    from edl_tpu.runtime.predict import (
        load_params_for_predict,
        load_rows,
        predict_batch,
    )

    workdir = args.workdir or tempfile.mkdtemp(prefix="ctr_real_")
    data_dir = os.path.join(workdir, "data")
    man = prepare(data_dir)
    print(f"prepared {man['n_samples']} real training rows -> {data_dir}")

    with ProcessJobLauncher(
        job="ctr_real",
        model="ctr",
        min_workers=1,
        max_workers=2,
        passes=args.passes,
        per_device_batch=32,
        data_dir=data_dir,
        export=True,
        ckpt_every=4,
        step_sleep_s=0.05,
        work_dir=workdir,
        extra_env={
            "EDL_VOCAB": str(VOCAB),
            "EDL_EVAL_DIR": os.path.join(data_dir, "eval"),
        },
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(2, timeout_s=180)
        launcher.scale_to(2)  # elastic mid-pass, reference demo style
        rcs = launcher.wait(timeout_s=360)
        assert all(rc == 0 for rc in rcs.values()), rcs
        assert launcher.kv("phase") == "succeeded"
        in_job_metric = launcher.kv("eval_metric")

    # re-score the final export exactly as `edl predict` would
    eval_rows = load_rows(data_dir=os.path.join(data_dir, "eval"), n_rows=4096)
    params, doc = load_params_for_predict(os.path.join(workdir, "export"))
    out = predict_batch(params, doc, eval_rows)
    auc = out["auc"]
    print(
        f"held-out AUC {auc:.4f} on real rows "
        f"(export step {doc['step']}; in-job eval_metric={in_job_metric})"
    )
    # real signal, real bar: malignancy is rankable far above coin-flip
    assert auc > 0.85, auc
    assert in_job_metric is not None, "worker never published eval_metric"
    return 0


if __name__ == "__main__":
    sys.exit(main())
