"""fit_a_line on REAL data — the diabetes dataset through the shard
pipeline, trained by real elastic worker processes, with a real eval.

Reference parity: the reference's fit_a_line trains uci_housing
(reference: example/fit_a_line/train_ft.py:20-31) from RecordIO shards
pre-baked into the job image (reference:
example/fit_a_line/Dockerfile:1-8) and its CTR example fetches AUC in
the train loop (reference: example/ctr/ctr/train.py:161-167). The TPU
shape of the same story:

1. prepare(): the scikit-learn-bundled diabetes dataset (442 real
   patient records, 10 features; Efron et al. 2004 — no download, the
   zero-egress analog of the pre-baked image) is standardized, split
   train/test, and written into ``runtime/shards.py`` format — the
   RecordIO-prebake analog;
2. an elastic multi-process job (ProcessJobLauncher -> worker_main)
   trains linreg from those shards via the coordinator's lease queue,
   scaling 1 -> 2 workers mid-pass, publishing a servable export at
   every commit + at stop;
3. the commit leader evaluates each export against the held-out split
   and publishes ``eval_metric`` (test RMSE) in coordinator KV — the
   AUC-in-the-train-loop analog — and this script re-checks the final
   export the same way a serving consumer would.

Run:  python examples/fit_a_line/real_data.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def prepare(data_dir: str, test_fraction: float = 0.1, seed: int = 0) -> dict:
    """Write the real diabetes rows as train shards + a held-out eval
    split (eval/ subdir, same shard format). Features are standardized
    and zero-padded from 10 to models.linreg.N_FEATURES (13, the
    uci_housing width the model is sized for); targets are scaled to
    unit variance so the loss curve is comparable across runs."""
    from sklearn.datasets import load_diabetes

    from edl_tpu.models import linreg
    from edl_tpu.runtime import shards

    ds = load_diabetes()
    x = ds.data.astype(np.float32)
    y = ds.target.astype(np.float32)[:, None]
    x = (x - x.mean(0)) / (x.std(0) + 1e-8)
    y = (y - y.mean()) / (y.std() + 1e-8)
    pad = linreg.N_FEATURES - x.shape[1]
    if pad > 0:
        x = np.concatenate([x, np.zeros((x.shape[0], pad), np.float32)], 1)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(x))
    n_test = max(1, int(len(x) * test_fraction))
    test, train = order[:n_test], order[n_test:]
    man = shards.write_shards(
        data_dir, {"x": x[train], "y": y[train]}, shard_size=64
    )
    shards.write_shards(
        os.path.join(data_dir, "eval"),
        {"x": x[test], "y": y[test]},
        shard_size=256,
    )
    return man


def rmse(params, x: np.ndarray, y: np.ndarray) -> float:
    from edl_tpu.models import linreg

    pred = np.asarray(linreg.predict(params, x))
    return float(np.sqrt(np.mean((pred - y) ** 2)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="")
    ap.add_argument("--passes", type=int, default=4)
    args = ap.parse_args()

    import tempfile

    from edl_tpu.runtime.export import load_export
    from edl_tpu.runtime.launcher import ProcessJobLauncher
    from edl_tpu.runtime.shards import FileShardSource

    workdir = args.workdir or tempfile.mkdtemp(prefix="fit_a_line_real_")
    data_dir = os.path.join(workdir, "data")
    man = prepare(data_dir)
    print(f"prepared {man['n_samples']} real training rows -> {data_dir}")

    ev = FileShardSource(os.path.join(data_dir, "eval"))
    eval_rows = ev.fetch_range(0, ev.n_samples)

    with ProcessJobLauncher(
        job="fit_a_line_real",
        model="linreg",
        min_workers=1,
        max_workers=2,
        passes=args.passes,
        per_device_batch=32,
        data_dir=data_dir,
        export=True,
        ckpt_every=4,
        step_sleep_s=0.05,
        work_dir=workdir,
        extra_env={"EDL_EVAL_DIR": os.path.join(data_dir, "eval")},
    ) as launcher:
        launcher.start(1)
        launcher.wait_progress(2, timeout_s=180)
        launcher.scale_to(2)  # elastic mid-pass, reference demo style
        rcs = launcher.wait(timeout_s=360)
        assert all(rc == 0 for rc in rcs.values()), rcs
        assert launcher.kv("phase") == "succeeded"
        in_job_metric = launcher.kv("eval_metric")

    params, doc = load_export(os.path.join(workdir, "export"))
    model_rmse = rmse(params, eval_rows["x"], eval_rows["y"])
    baseline = float(np.sqrt(np.mean((eval_rows["y"] - eval_rows["y"].mean()) ** 2)))
    print(
        f"test RMSE {model_rmse:.4f} vs predict-the-mean {baseline:.4f} "
        f"(export step {doc['step']}; in-job eval_metric={in_job_metric})"
    )
    assert model_rmse < 0.85 * baseline, (model_rmse, baseline)
    assert in_job_metric is not None, "worker never published eval_metric"
    return 0


if __name__ == "__main__":
    sys.exit(main())
