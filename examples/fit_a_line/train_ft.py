"""Elastic fault-tolerant fit_a_line — the reference's flagship demo.

Port of reference example/fit_a_line/train_ft.py:33-114: an elastic
trainer that pulls work from a lease-based task queue so workers can
come and go mid-pass, retargeted by the autoscaler. TPU-native shape:
the pserver/etcd runtime is replaced by an in-mesh data-parallel
trainer that reshards in place on each scale event (zero restarts).

Run (hardware-free, 8-device virtual CPU mesh):
    python examples/fit_a_line/train_ft.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from edl_tpu.utils.platform import force_virtual_cpu  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--samples", type=int, default=4096)
    ap.add_argument("--chunk", type=int, default=128)
    ap.add_argument("--kill-one-worker", action="store_true",
                    help="fail a worker mid-pass to demo fault tolerance")
    args = ap.parse_args()

    force_virtual_cpu(args.devices)

    import jax
    import numpy as np
    import optax

    from edl_tpu.api.job import JobPhase, TrainingJob
    from edl_tpu.cluster.fake import FakeCluster, FakeHost
    from edl_tpu.controller.controller import Controller
    from edl_tpu.models import linreg
    from edl_tpu.monitor.collector import ClusterSource, Collector
    from edl_tpu.runtime.data import ElasticDataQueue, QueueBatcher
    from edl_tpu.runtime.local import LocalJobRunner

    # Synthetic fleet: one chip per host so the elastic range is visible.
    cluster = FakeCluster(
        hosts=[FakeHost(f"h{i}", 8000, 16000, 1) for i in range(args.devices)]
    )
    ctl = Controller(cluster, max_load_desired=1.0)

    job = TrainingJob.from_yaml_file(
        os.path.join(os.path.dirname(__file__), "job.yaml")
    )
    cluster.submit_job(job)
    ctl.step()
    assert ctl.phase_of(job.name) == JobPhase.RUNNING
    print(f"submitted {job.name}: workers start at {job.status.parallelism}")

    # The master-task-queue analog: chunked sample leases with timeout
    # redelivery (reference: cloud_reader train_ft.py:111-114).
    queue = ElasticDataQueue(
        n_samples=args.samples, chunk_size=args.chunk, passes=job.spec.passes
    )
    x, y = linreg.synthetic_dataset(args.samples)
    batcher = QueueBatcher(
        queue, lambda t: {"x": x[t.start : t.end], "y": y[t.start : t.end]}
    )

    def data_fn(bs):
        b = batcher.next_batch(bs)
        if b is None:
            return {"x": x[:bs], "y": y[:bs]}
        if b["x"].shape[0] < bs:
            b = {k: np.resize(v, (bs,) + v.shape[1:]) for k, v in b.items()}
        return b

    runner = LocalJobRunner(
        ctl,
        job,
        linreg.loss_fn,
        optax.sgd(0.05),
        linreg.init_params(jax.random.PRNGKey(0)),
        per_chip_batch=16,
    )
    runner.trainer.train_steps(data_fn, 3)

    # Idle fleet -> the autoscaler grows the job; training reshards
    # in place at the next step boundary.
    ctl.autoscaler.tick()
    runner.trainer.train_steps(data_fn, 3)  # reshard up happens here

    if args.kill_one_worker:
        # A host dies mid-pass: the runtime reshards down to the live
        # membership and the dead worker's leased chunks are redelivered
        # (reference: master task queue redispatch, docker/paddle_k8s:28-31).
        victim = next(
            p for p in cluster.pods.values()
            if p.role == "worker" and p.host is not None
        )
        print(f"host {victim.host} dies (taking worker pod {victim.name})")
        cluster.remove_host(victim.host)
        queue.release_worker("w-dead")
        cluster.reconcile()

    report = runner.run(data_fn, queue=queue)

    sample = ClusterSource(cluster).sample()
    print(sample.render())
    print(
        f"done: phase={ctl.phase_of(job.name).value} "
        f"steps={int(runner.trainer.state.step)} "
        f"final_loss={report.losses[-1]:.4f} "
        f"reshards={[f'{e.from_workers}->{e.to_workers} {e.stall_s * 1e3:.0f}ms' for e in report.reshards]}"
    )
    assert ctl.phase_of(job.name) == JobPhase.SUCCEEDED
    assert report.losses[-1] < report.losses[0]
    return 0


if __name__ == "__main__":
    sys.exit(main())
