"""Local (non-distributed) fit_a_line baseline.

Port of reference example/fit_a_line/train_local.py:41-106: the same
model and data as train_ft.py with no control plane — one device, a
plain jitted SGD loop, parameters saved per pass.

Run: python examples/fit_a_line/train_local.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--save-dir", default=None,
                    help="save params per pass (reference: save_parameter_to_tar)")
    args = ap.parse_args()

    import jax
    import numpy as np
    import optax

    from edl_tpu.models import linreg

    x, y = linreg.synthetic_dataset(args.samples)
    params = linreg.init_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.05)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(linreg.loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    n_batches = args.samples // args.batch
    for p in range(args.passes):
        loss = None
        for i in range(n_batches):
            lo = i * args.batch
            batch = {"x": x[lo : lo + args.batch], "y": y[lo : lo + args.batch]}
            params, opt_state, loss = step(params, opt_state, batch)
        print(f"pass {p}: loss={float(loss):.6f}")
        if args.save_dir:
            os.makedirs(args.save_dir, exist_ok=True)
            path = os.path.join(args.save_dir, f"pass-{p}.npz")
            np.savez(path, **{k: np.asarray(v) for k, v in params.items()})
            print(f"  saved {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
