"""Classic (non-elastic) distributed digit recognition.

Port of reference example/fit_a_line/fluid/recognize_digits.py:107-145
(W3): the DistributeTranspiler-era mode — a FIXED worker count for the
life of the job, each worker reading its static data shard
(``idx % trainers == trainer_id``, reference:
example/fit_a_line/fluid/common.py:24-40), with a per-epoch checkpoint
(reference: recognize_digits.py:84-88). TPU-native shape: the
pserver/trainer role split becomes one SPMD data-parallel mesh; the
static file shards become ``StaticShardReader`` chunk ownership; the
conv net runs in XLA (MXU convolutions) instead of fluid.

Run (hardware-free, 8-device virtual CPU mesh):
    python examples/recognize_digits/train.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from edl_tpu.utils.platform import force_virtual_cpu  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--samples", type=int, default=2048)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=None,
                    help="defaults to the manifest's spec.passes")
    ap.add_argument("--per-worker-batch", type=int, default=32)
    ap.add_argument(
        "--real-data",
        action="store_true",
        help="train on the REAL scikit-learn-bundled digits dataset "
        "(1797 handwritten 8x8 images; the MNIST-class analog of the "
        "reference's recognize_digits) with a held-out accuracy eval "
        "per epoch, instead of the synthetic pattern",
    )
    args = ap.parse_args()

    force_virtual_cpu(args.devices)

    import jax
    import numpy as np
    import optax

    from edl_tpu.api.job import JobPhase, TrainingJob
    from edl_tpu.cluster.fake import FakeCluster, FakeHost
    from edl_tpu.controller.controller import Controller
    from edl_tpu.models import resnet
    from edl_tpu.runtime import checkpoint
    from edl_tpu.runtime.data import StaticShardReader
    from edl_tpu.runtime.local import LocalJobRunner

    cluster = FakeCluster(
        hosts=[FakeHost(f"h{i}", 8000, 16000, 1) for i in range(args.devices)]
    )
    ctl = Controller(cluster, max_load_desired=1.0)

    job = TrainingJob.from_yaml_file(
        os.path.join(os.path.dirname(__file__), "job.yaml")
    )
    cluster.submit_job(job)
    ctl.step()
    assert ctl.phase_of(job.name) == JobPhase.RUNNING
    n_workers = job.status.parallelism
    assert not job.elastic(), "this is the fixed-membership mode"
    print(f"submitted {job.name}: fixed {n_workers} workers")
    if args.epochs is None:
        args.epochs = job.spec.passes  # manifest is the single source
    if args.epochs < 1:
        ap.error(f"--epochs/spec.passes must be >= 1, got {args.epochs}")
    # Static shards: worker w owns chunks w, w+N, w+2N, ... — disjoint,
    # covering every sample exactly once per epoch.
    cfg = resnet.ResNetConfig.tiny()
    rng = np.random.RandomState(0)
    test = None
    if args.real_data:
        # real handwritten digits (Alpaydin & Kaynak, bundled with
        # scikit-learn — zero egress): 8x8 grayscale upsampled 2x and
        # tiled to the model's 3-channel input, unit-normalized, with a
        # held-out split for a REAL accuracy eval (reference parity:
        # recognize_digits trains real MNIST)
        from sklearn.datasets import load_digits

        ds = load_digits()
        x = (ds.images / 16.0).astype(np.float32)  # [N, 8, 8]
        x = np.kron(x, np.ones((1, 2, 2), np.float32))  # -> [N, 16, 16]
        x = np.repeat(x[..., None], 3, axis=-1)  # -> [N, 16, 16, 3]
        y = ds.target.astype(np.int32)
        order = rng.permutation(len(x))
        n_test = len(x) // 10
        ti, tr = order[:n_test], order[n_test:]
        test = {"images": x[ti], "label": y[ti]}
        data = {"images": x[tr], "label": y[tr]}
        args.samples = len(tr)
        print(f"real digits: {len(tr)} train / {n_test} held-out rows")
    else:
        data = resnet.synthetic_batch(rng, args.samples, size=16)
    # every worker must own at least one chunk: shrink chunks if the
    # dataset is small rather than dividing by an empty shard. Runs
    # AFTER --real-data has replaced args.samples with the real row
    # count — clamping against the pre-override value can still leave
    # a worker with an empty shard.
    args.chunk = min(args.chunk, max(args.samples // n_workers, 1))
    readers = [
        StaticShardReader(args.samples, args.chunk, n_workers, w)
        for w in range(n_workers)
    ]
    shards = [np.asarray(r.epoch_indices(), np.int64) for r in readers]
    cursors = [0] * n_workers

    def data_fn(global_bs):
        # each worker contributes an equal slice of the global batch from
        # its own shard, wrapping within the shard across epochs
        per = global_bs // n_workers
        parts = []
        for w in range(n_workers):
            take = np.arange(cursors[w], cursors[w] + per) % len(shards[w])
            cursors[w] = (cursors[w] + per) % len(shards[w])
            parts.append(shards[w][take])
        idx = np.concatenate(parts)
        return {k: v[idx] for k, v in data.items()}

    runner = LocalJobRunner(
        ctl,
        job,
        resnet.make_loss_fn(cfg),
        optax.adam(1e-3),
        resnet.init_params(jax.random.PRNGKey(0), cfg),
        per_chip_batch=args.per_worker_batch,
    )

    def test_accuracy():
        if test is None:
            return None
        logits = resnet.forward(
            runner.trainer.state.params, test["images"], cfg
        )
        return float(np.mean(np.argmax(np.asarray(logits), -1) == test["label"]))

    steps_per_epoch = max(args.samples // (args.per_worker_batch * n_workers), 1)
    ckpt_dir = tempfile.mkdtemp(prefix="digits_ckpt_")
    report = None
    acc = None
    for epoch in range(args.epochs):
        report = runner.trainer.train_steps(data_fn, steps_per_epoch)
        # per-epoch checkpoint (reference: recognize_digits.py:84-88
        # save_inference_model each epoch)
        path = os.path.join(ckpt_dir, f"epoch_{epoch}")
        checkpoint.save(path, runner.trainer.state, {"epoch": epoch})
        acc = test_accuracy()
        print(
            f"epoch {epoch}: loss {report.losses[-1]:.4f} "
            + (f"test_acc {acc:.3f} " if acc is not None else "")
            + f"(ckpt -> {path})"
        )
    runner.run(data_fn, n_steps=1)  # final step + mark complete
    if acc is not None:
        # real-data bar: clearly better than the 10-class chance floor
        assert acc > 0.5, f"held-out accuracy {acc} barely above chance"

    assert ctl.phase_of(job.name) == JobPhase.SUCCEEDED
    assert report.losses[-1] < report.losses[0] * 1.05
    # shard audit: disjoint and complete coverage
    all_idx = np.sort(np.concatenate(shards))
    assert np.array_equal(all_idx, np.arange(args.samples))
    print(
        f"done: phase=succeeded workers={n_workers} "
        f"epochs={args.epochs} final_loss={report.losses[-1]:.4f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
